"""The nemesis scenario DSL — composed faults as reproducible schedules.

A scenario is a fixed workload (the same seeded synthetic-ratings
stream every parity test in this repo trains on) plus an ordered list
of :class:`NemesisOp`\\ s, each keyed to a TRAINING ROUND rather than a
wall-clock instant: op ``k`` fires once any worker reaches
``at_round`` (the nemesis thread waits on the round counter, then
executes ops in list order).  Round-keyed schedules are what make a
failing run reproducible from its ``(seed, schedule)`` pair — the
schedule says *where in the computation* each fault landed, not when
on somebody's laptop clock.

Ops come in two vocabularies, deliberately mixed (the Jepsen recipe —
a nemesis composes network faults WITH cluster operations):

  * **wire ops** → the shard's :class:`~.proxy.ChaosProxy`:
    ``partition`` (one-way/two-way, optionally self-healing after
    ``ms``), ``heal``, ``delay``/``clear_delay``, ``drip``/
    ``clear_drip``, ``truncate_next``, ``dup_next``, ``reorder_next``,
    ``half_open``;
  * **cluster ops** → the driver: ``kill_shard``, ``replace_shard``,
    ``promote_shard``, ``scale_out``, ``scale_in``, ``sleep``, and
    ``corrupt_row`` — a SILENT out-of-band row perturbation (no WAL,
    no ledger entry: simulated bit-rot) whose only witness is the
    final-table parity checker.  It exists to prove the checkers can
    catch a real violation; every other op the stack must survive.

Serialization is canonical (sorted keys, no whitespace): a schedule
round-trips byte-identically through :meth:`Scenario.to_json` /
:meth:`Scenario.from_json`, which is the regression-corpus contract
(``nemesis/corpus/``) and what the shrinker's minimized output is
committed as.

``BUILTIN_SCENARIOS`` is the fixed-seed battery tier-1 replays —
fourteen scenarios covering every proxy fault class, including the
asymmetric partition splitting a live migration,
kill-primary-under-partition, the partition-client-mid-lease schedule
proving the hot-key cache's staleness bound holds through a fault
(hotcache/, docs/hotcache.md), and the two ROADMAP-5 full-stack
workload scenarios (``pa_full_stack``, ``sketch_full_stack``:
train-while-serve-while-resize-while-faulted for the non-MF learners,
workloads/ + docs/workloads.md), and the ISSUE-20
``kill_promote_cold_tier`` anchor (failover over a mostly-demoted
two-tier store, tierstore/ + docs/tierstore.md) — plus
``VIOLATION_SCENARIO``, the
deliberately seeded corruption the checkers must catch.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

import numpy as np

WIRE_ACTIONS = frozenset({
    "partition", "heal", "delay", "clear_delay", "drip", "clear_drip",
    "truncate_next", "dup_next", "reorder_next", "half_open",
})
CLUSTER_ACTIONS = frozenset({
    "kill_shard", "replace_shard", "promote_shard", "scale_out",
    "scale_in", "sleep", "corrupt_row",
})
ACTIONS = WIRE_ACTIONS | CLUSTER_ACTIONS


@dataclasses.dataclass(frozen=True)
class NemesisOp:
    """One scheduled operation.  ``ms`` is overloaded per action:
    partition self-heal duration, delay per-frame latency, sleep
    duration.  ``mode`` is the wire direction (``c2s``/``s2c``/
    ``both``); one-shot frame faults default ``mode='both'`` to the
    direction named in their docstring (``s2c`` — responses)."""

    at_round: int
    action: str
    shard: int = 0
    mode: str = "both"
    ms: float = 0.0
    jitter_ms: float = 0.0
    bytes_per_sec: float = 0.0
    keep_frac: float = 0.35
    count: int = 1
    gid: int = 0
    # truncate_next aim over BINARY frames (utils/frames.py): "frame"
    # cuts anywhere (keep_frac of the bytes — the line-protocol cut
    # too), "header" tears inside the 24-byte fixed header (the
    # length prefix never completes), "payload" past it (the length
    # promised more than EOF delivered)
    cut: str = "frame"

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"action {self.action!r}: one of {sorted(ACTIONS)}"
            )
        if self.at_round < 0:
            raise ValueError(f"at_round={self.at_round}: must be >= 0")

    def to_dict(self) -> dict:
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
        }


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One reproducible experiment: workload shape + op schedule.

    ``parity=True`` runs the fault-free oracle on the same stream and
    requires the final table allclose-equal (only meaningful under
    BSP, ``staleness_bound=0`` — SSP reorders updates by design).
    ``expect`` records the corpus contract: ``"pass"`` scenarios must
    satisfy every checker; ``"violation"`` scenarios must FAIL one
    (they pin that the checkers still catch what they exist to catch).
    """

    name: str
    ops: Tuple[NemesisOp, ...]
    seed: int = 0
    # the registered workload this scenario trains (workloads/
    # registry.py): "mf" | "pa" | "sketch" — the runner resolves the
    # logic, stream, init and PARITY MODE (allclose for MF, bitwise
    # for PA, integer-exact for sketches) through the registry, so
    # one schedule vocabulary drives every learner
    workload: str = "mf"
    rounds: int = 12
    batch: int = 96
    num_users: int = 48
    num_items: int = 64
    dim: int = 4
    num_shards: int = 2
    num_workers: int = 2
    staleness_bound: Optional[int] = 0
    replicated: bool = False
    parity: bool = True
    serving_reads: bool = True
    # the reader thread serves through a client-edge hot-key lease
    # cache (hotcache/, docs/hotcache.md) and the run must satisfy the
    # lease_staleness invariant — no cached row served past the bound,
    # through whatever the schedule does to the wire.  Workers stay
    # BSP-uncached (the carve-out), so parity remains meaningful.
    hotcache: bool = False
    # client payload encoding (cluster/driver.py ClusterConfig):
    # "b64" = the exact default; "q8"/"bf16" replay the schedule over
    # QUANTIZED-enc connections (compression/, docs/compression.md) —
    # the torn-quantized-frame regression rides this field.  BSP
    # scenarios keep parity either way (the driver's bound-0 carve-out
    # downgrades worker clients to exact fp32).
    wire_format: str = "b64"
    request_timeout: float = 15.0
    retry_timeout: float = 60.0
    # straggler-adaptive runtime (adaptive/, docs/adaptive.md): the
    # driver gets the AdaptiveClock + push hedging kill switch and the
    # runner attaches a timeline-fed AdaptiveRuntime, samples the
    # per-worker effective bounds live, and audits the
    # adaptive_bound_envelope invariant.  The staleness check then
    # judges the spread against the CEILING (widened allowances
    # legally raise the spread to ceiling + 1).
    adaptive: bool = False
    # two-tier parameter store (tierstore/, docs/tierstore.md): the
    # shard slices run store_backend="tiered" with a DELIBERATELY tiny
    # hot tier, so the schedule's reads and the recovery paths (WAL
    # replay, promotion catch-up) must cross the demoted cold set.
    # The runner samples per-shard tier stats live and audits the
    # tier_residency invariant: resident rows never exceed the
    # configured hot capacity, at any sample, through every fault.
    tiered: bool = False
    tier_hot_rows: int = 24
    expect: str = "pass"

    def __post_init__(self):
        if self.expect not in ("pass", "violation"):
            raise ValueError(f"expect={self.expect!r}: 'pass' | 'violation'")
        if self.parity and self.staleness_bound != 0:
            raise ValueError(
                f"{self.name}: parity vs the fault-free oracle needs "
                f"BSP (staleness_bound=0) — SSP reorders updates"
            )

    # -- canonical JSON (the corpus / shrinker round-trip contract) --------
    def to_json(self) -> str:
        doc = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if f.name != "ops"
        }
        doc["ops"] = [op.to_dict() for op in self.ops]
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "Scenario":
        doc = json.loads(text)
        ops = tuple(NemesisOp(**op) for op in doc.pop("ops"))
        return cls(ops=ops, **doc)

    def with_ops(self, ops) -> "Scenario":
        return dataclasses.replace(self, ops=tuple(ops))

    # -- the randomized search's generator ---------------------------------
    @classmethod
    def from_seed(cls, seed: int, **overrides) -> "Scenario":
        """Sample a small schedule deterministically: 2–4 ops from the
        survivable vocabulary, faults landing in the middle half of the
        stream so the run both feels them and recovers.  Same seed ⇒
        same scenario, any host — the search's failures are replayable
        by seed alone."""
        rng = np.random.default_rng(seed)
        rounds = int(overrides.get("rounds", 12))
        num_shards = int(overrides.get("num_shards", 2))
        n_ops = int(rng.integers(2, 5))
        ops = []
        for _ in range(n_ops):
            at = int(rng.integers(rounds // 4, max(rounds // 4 + 1,
                                                   (3 * rounds) // 4)))
            shard = int(rng.integers(0, num_shards))
            kind = int(rng.integers(0, 4))
            if kind == 0:
                ops.append(NemesisOp(
                    at, "partition", shard=shard,
                    mode=["both", "c2s", "s2c"][int(rng.integers(0, 3))],
                    ms=float(rng.uniform(100.0, 300.0)),
                ))
            elif kind == 1:
                ops.append(NemesisOp(
                    at, "delay", shard=shard,
                    ms=float(rng.uniform(2.0, 15.0)),
                    jitter_ms=float(rng.uniform(0.0, 10.0)),
                ))
                ops.append(NemesisOp(
                    min(rounds - 1, at + int(rng.integers(1, 4))),
                    "clear_delay", shard=shard,
                ))
            elif kind == 2:
                ops.append(NemesisOp(
                    at, "truncate_next", shard=shard,
                    mode=["c2s", "s2c"][int(rng.integers(0, 2))],
                    keep_frac=float(rng.uniform(0.1, 0.9)),
                ))
            else:
                ops.append(NemesisOp(
                    at, "kill_shard", shard=shard,
                ))
                ops.append(NemesisOp(
                    at, "replace_shard", shard=shard,
                ))
        ops.sort(key=lambda o: o.at_round)
        overrides.setdefault("name", f"rand-{seed}")
        overrides.setdefault("seed", int(seed))
        return cls(ops=tuple(ops), **overrides)


# ---------------------------------------------------------------------------
# the fixed-seed battery (tier-1 replays these from nemesis/corpus/)
# ---------------------------------------------------------------------------

BUILTIN_SCENARIOS: Tuple[Scenario, ...] = (
    # 1. a clean two-way partition that heals: clients stall, retry,
    # converge — zero lost/duplicated updates, parity holds
    Scenario(
        "two_way_partition_heal",
        (NemesisOp(3, "partition", shard=0, mode="both", ms=250.0),),
        seed=101,
    ),
    # 2. one-way partition: requests blackholed, responses flow — the
    # half of a partial partition a liveness check built on responses
    # alone would miss
    Scenario(
        "one_way_partition_c2s",
        (NemesisOp(4, "partition", shard=1, mode="c2s", ms=250.0),),
        seed=102,
    ),
    # 3. ISSUE anchor: an ASYMMETRIC partition splits a live migration
    # mid-flight — scale-out's xfer/load traffic crosses the mesh, the
    # s2c leg stalls, the migration waits it out, the flip still
    # verifies bitwise
    Scenario(
        "asym_partition_during_migration",
        (
            NemesisOp(4, "partition", shard=0, mode="s2c", ms=300.0),
            NemesisOp(4, "scale_out"),
        ),
        seed=103,
        rounds=14,
    ),
    # 4. ISSUE anchor: kill-primary-under-partition — the shard dies
    # WHILE clients are partitioned from it; replacement publishes a
    # fresh address under a new epoch and everyone converges
    Scenario(
        "kill_primary_under_partition",
        (
            NemesisOp(4, "partition", shard=1, mode="both", ms=300.0),
            NemesisOp(4, "kill_shard", shard=1),
            NemesisOp(4, "replace_shard", shard=1),
        ),
        seed=104,
        rounds=14,
    ),
    # 5. promote-while-client-partitioned: replica chains — the dead
    # primary's clients are partitioned from its proxy; promotion
    # flips the epoch to the follower's (un-partitioned) address
    Scenario(
        "promote_while_client_partitioned",
        (
            NemesisOp(4, "partition", shard=0, mode="c2s", ms=300.0),
            NemesisOp(4, "kill_shard", shard=0),
            NemesisOp(4, "promote_shard", shard=0),
        ),
        seed=105,
        rounds=14,
        replicated=True,
    ),
    # 6. scale-out-during-drip: the link is bandwidth-starved while the
    # migration's bulk xfer crosses it
    Scenario(
        "scale_out_during_drip",
        (
            NemesisOp(3, "drip", shard=0, bytes_per_sec=200_000.0),
            NemesisOp(5, "scale_out"),
            NemesisOp(7, "clear_drip", shard=0),
        ),
        seed=106,
        rounds=14,
    ),
    # 7. slow-shard straggler storm under SSP: one shard's frames are
    # delayed+jittered for a window; the staleness bound must hold
    # (parity is off — SSP reorders updates by design).  Runs with the
    # adaptive runtime live: the per-worker effective bounds are
    # sampled through the storm and the adaptive_bound_envelope
    # invariant must hold (satellite of ISSUE 19).
    Scenario(
        "straggler_storm_ssp",
        (
            NemesisOp(3, "delay", shard=0, ms=10.0, jitter_ms=8.0),
            NemesisOp(8, "clear_delay", shard=0),
        ),
        seed=107,
        rounds=14,
        staleness_bound=2,
        parity=False,
        adaptive=True,
    ),
    # 8. mid-frame RST on a pull RESPONSE: the payload is torn
    # mid-frame and the connection reset — the client replays; pulls
    # are idempotent, parity holds.  Over the binary transport the
    # two cuts are AIMED: one inside the 24-byte fixed header (the
    # length prefix never completes), one inside the row payload (the
    # length promised more than EOF delivered) — the two torn-read
    # shapes a length-prefixed reader must survive.
    Scenario(
        "mid_frame_rst_pull",
        (
            NemesisOp(3, "truncate_next", shard=0, mode="s2c",
                      keep_frac=0.4, cut="header"),
            NemesisOp(7, "truncate_next", shard=0, mode="s2c",
                      keep_frac=0.7, cut="payload"),
        ),
        seed=108,
    ),
    # 9. mid-frame RST on a push REQUEST: the delta payload dies
    # mid-wire; the replay carries the same pid, the (pid,id) ledger
    # absorbs any half-applied ambiguity — exactly-once audit
    # balances.  Same header/payload aim as #8, on the request leg.
    Scenario(
        "mid_frame_rst_push",
        (
            NemesisOp(3, "truncate_next", shard=0, mode="c2s",
                      keep_frac=0.3, cut="header"),
            NemesisOp(7, "truncate_next", shard=1, mode="c2s",
                      keep_frac=0.6, cut="payload"),
        ),
        seed=109,
    ),
    # 10. ISSUE-11 anchor: partition the CLIENT mid-lease — the reader
    # holds hot-key leases (hotcache/) when shard 0's link blackholes
    # both ways, then shard 1's response leg stalls.  Piggybacked
    # invalidations cannot arrive through a partition, which is exactly
    # the case the client-local staleness bound exists for: the
    # lease_staleness checker proves no cached row was ever served
    # past the bound, while cached hits keep the serving error budget
    # clean through the fault window.
    Scenario(
        "partition_client_mid_lease",
        (
            NemesisOp(3, "partition", shard=0, mode="both", ms=250.0),
            NemesisOp(6, "partition", shard=1, mode="s2c", ms=150.0),
        ),
        seed=111,
        rounds=14,
        hotcache=True,
    ),
    # 11. half-open accept: the dial succeeds, the server never answers
    # — the client's read deadline, not the connect, is what saves it.
    # The preceding mid-frame RST kills the pooled connection, so the
    # redial is what lands on the half-open accept (pooled connections
    # never re-dial on their own).
    Scenario(
        "half_open_accept",
        (
            NemesisOp(3, "half_open", shard=0, count=1),
            NemesisOp(3, "truncate_next", shard=0, mode="s2c",
                      keep_frac=0.5),
        ),
        seed=110,
        request_timeout=1.0,
    ),
    # 12. ROADMAP-5 acceptance, PA: the passive-aggressive classifier
    # through the FULL stack — train-while-serve-while-resize-while-
    # faulted: a both-ways partition, a live scale-out, then
    # kill-primary→promote, with the serving reader issuing `predict`
    # probes throughout.  num_workers=1 because the parity bar is
    # BITWISE (workloads/pa.py: with one writer the dense-combined
    # update order is structurally deterministic; two writers'
    # interleaved fp32 adds are not associative).
    Scenario(
        "pa_full_stack",
        (
            NemesisOp(3, "partition", shard=0, mode="both", ms=250.0),
            NemesisOp(5, "scale_out"),
            NemesisOp(8, "kill_shard", shard=1),
            NemesisOp(8, "promote_shard", shard=1),
        ),
        seed=112,
        rounds=14,
        num_workers=1,
        replicated=True,
        workload="pa",
    ),
    # 13. ROADMAP-5 acceptance, sketches: the count-min layer through
    # the same resize+failover gauntlet PLUS a mid-frame RST on a push
    # request — the torn-frame replay must not lose or double a single
    # increment.  wire_format="q8" is REQUESTED to pin the
    # increment-semantics carve-out: the driver bypasses quantization
    # for increment workloads, so counts stay integer-exact (the
    # parity checker runs with no float tolerance) even though the
    # config asked for the quantized codec.  Two workers: integer adds
    # commute, so exactness must survive interleaving too.
    # 14. ISSUE-20 anchor: kill→promote over a COLD tier — the whole
    # chain runs store_backend="tiered" with a hot tier far smaller
    # than the table (24 rows vs a 56-row slice), so by round 4 most
    # mutated rows live in the mmap cold slab.  Killing the primary
    # and promoting its follower forces the promotion catch-up (WAL
    # tail drain) and the post-flip serving reads through demoted
    # rows; parity against the all-RAM oracle proves the tier swap is
    # invisible to correctness, and the sampled tier_residency
    # invariant proves the resident set stayed within the configured
    # hot capacity throughout.
    Scenario(
        "kill_promote_cold_tier",
        (
            NemesisOp(4, "kill_shard", shard=0),
            NemesisOp(4, "promote_shard", shard=0),
        ),
        seed=114,
        rounds=14,
        replicated=True,
        tiered=True,
    ),
    Scenario(
        "sketch_full_stack",
        (
            NemesisOp(3, "truncate_next", shard=0, mode="c2s",
                      keep_frac=0.5, cut="payload"),
            NemesisOp(4, "partition", shard=1, mode="both", ms=250.0),
            NemesisOp(6, "scale_out"),
            NemesisOp(9, "kill_shard", shard=0),
            NemesisOp(9, "promote_shard", shard=0),
        ),
        seed=113,
        rounds=14,
        replicated=True,
        workload="sketch",
        wire_format="q8",
    ),
)

# The deliberately seeded invariant violation (NOT part of the passing
# battery): silent out-of-band row corruption buried in survivable
# noise ops.  The parity checker must catch it; the shrinker must
# reduce the schedule to the single corrupt_row op.
VIOLATION_SCENARIO = Scenario(
    "seeded_corruption",
    (
        NemesisOp(2, "delay", shard=0, ms=2.0),
        NemesisOp(4, "clear_delay", shard=0),
        NemesisOp(5, "corrupt_row", shard=0, gid=7),
        NemesisOp(7, "partition", shard=1, mode="both", ms=100.0),
    ),
    seed=666,
    rounds=10,
    serving_reads=False,
    expect="violation",
)


__all__ = [
    "ACTIONS",
    "BUILTIN_SCENARIOS",
    "CLUSTER_ACTIONS",
    "NemesisOp",
    "Scenario",
    "VIOLATION_SCENARIO",
    "WIRE_ACTIONS",
]
