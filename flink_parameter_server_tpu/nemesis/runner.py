"""The nemesis runner — proxied clusters, scenario execution, search,
shrinking, and the committed regression corpus.

Execution model of one scenario (:func:`run_scenario`):

  1. build the fault-free **oracle** table for the scenario's stream
     (cached per workload shape — every parity scenario on the same
     stream shares one oracle run);
  2. build a **proxied** elastic (or replicated) cluster: every shard's
     front door is a :class:`~.proxy.ChaosProxy`, spliced in by
     :class:`~.proxy.ProxiedServer` so worker clients, the migration
     data plane and replication heartbeats all cross the mesh;
  3. train the scenario's REGISTERED workload (``Scenario.workload`` →
     workloads/registry.py: MF, the PA classifier, or the count-min
     sketch layer — the same seeded stream its oracle saw) while a
     dedicated nemesis thread waits on the ROUND counter and fires the
     schedule's ops in order, a reader thread issues serving pulls
     PLUS the workload's own serving probes (predict / query / topk)
     through its own membership client, and a sampler polls the
     staleness spread;
  4. tear everything down and run the invariant checkers
     (:mod:`.invariants`); on failure, dump the flight recorder and
     the canonical schedule JSON — the ``(seed, schedule)`` pair any
     failure replays from.

:func:`search_scenarios` is the randomized layer: seeds →
:meth:`Scenario.from_seed` schedules → failures, each reproducible by
its seed.  :func:`shrink` is the delta-debugging layer: greedily drop
ops while the failure persists, so the corpus commits MINIMAL failing
schedules.  :func:`replay_corpus` re-runs every committed schedule and
checks its recorded expectation — pass scenarios must pass every
checker, violation scenarios must still be CAUGHT (a checker that
stops catching its seeded violation is itself a regression).
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..elastic.controller import ElasticClusterConfig, ElasticClusterDriver
from ..replication.driver import (
    ReplicatedClusterConfig,
    ReplicatedClusterDriver,
)
from ..telemetry import flightrec
from ..telemetry.registry import MetricsRegistry
from .invariants import (
    AdaptiveBoundSampler,
    StalenessSampler,
    ThreadLedger,
    TierResidencySampler,
    Verdict,
    check_adaptive_bound,
    check_exactly_once,
    check_lease_staleness,
    check_lock_inversions,
    check_no_errors,
    check_serving_budget,
    check_staleness,
    check_tier_residency,
)

# the cached reader's staleness bound, in ticks (1 tick = 1 reader
# pull): what the lease_staleness verdict of a hotcache scenario is
# checked against
HOTCACHE_READER_BOUND = 3
from .proxy import ChaosProxy, ProxiedServer
from .scenarios import (
    BUILTIN_SCENARIOS,
    NemesisOp,
    Scenario,
    VIOLATION_SCENARIO,
)

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "corpus")


# ---------------------------------------------------------------------------
# proxied drivers — the mesh splice
# ---------------------------------------------------------------------------


class _NemesisMeshMixin:
    """Route every shard front door through a :class:`ChaosProxy`.

    ``_build_shard`` is the one chokepoint both elastic drivers use
    for initial spin-up, scale-out and dead-shard replacement — the
    proxy is created there and the returned server is the
    :class:`ProxiedServer` façade, so every address the driver ever
    publishes is a mesh address.  ``mesh`` maps shard id → its CURRENT
    proxy (replacements swap it); ``mesh_history`` keeps every proxy
    ever created so fault counts survive replacement."""

    def __init__(self, logic, *, nemesis_seed: int = 0, **kwargs):
        self.mesh: Dict[int, ChaosProxy] = {}
        self.mesh_history: List[ChaosProxy] = []
        self._nemesis_seed = int(nemesis_seed)
        super().__init__(logic, **kwargs)

    def _build_shard(self, shard_id, partitioner=None):
        shard, server = super()._build_shard(shard_id, partitioner)
        proxy = ChaosProxy(
            server.host, server.port,
            name=f"nemesis-{shard_id}",
            seed=self._nemesis_seed + int(shard_id),
            registry=self.registry if self.registry is not None else False,
        ).start()
        self.mesh[int(shard_id)] = proxy
        self.mesh_history.append(proxy)
        return shard, ProxiedServer(server, proxy)

    def stop(self) -> None:
        super().stop()
        for proxy in self.mesh_history:
            proxy.stop()  # idempotent; covers promoted-over proxies
        self.mesh = {}

    def faults_injected(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for proxy in self.mesh_history:
            for kind, n in proxy.faults.items():
                out[kind] = out.get(kind, 0) + n
        return out


class NemesisElasticDriver(_NemesisMeshMixin, ElasticClusterDriver):
    """Elastic cluster with every shard link behind the chaos mesh."""


class NemesisReplicatedDriver(_NemesisMeshMixin, ReplicatedClusterDriver):
    """Replicated cluster (replica chains) behind the chaos mesh —
    primaries are proxied; follower replication legs dial directly
    (their stream has its own fault hooks, resilience/chaos.py)."""


# ---------------------------------------------------------------------------
# workload / oracle (workloads/registry.py: any registered learner)
# ---------------------------------------------------------------------------

_ORACLE_CACHE: Dict[Tuple, np.ndarray] = {}


def _make_workload(s: Scenario):
    """Resolve the scenario's workload through the registry — the
    stream/data seed is FIXED (WorkloadParams.seed default) so every
    scenario on the same shape shares one stream and one oracle;
    ``s.seed`` seeds the FAULTS, not the data."""
    from ..workloads import WorkloadParams, create_workload

    return create_workload(s.workload, WorkloadParams(
        rounds=s.rounds, batch=s.batch, num_users=s.num_users,
        num_items=s.num_items, dim=s.dim, num_workers=s.num_workers,
    ))


def oracle_values(s: Scenario) -> np.ndarray:
    """The fault-free final table for the scenario's stream, under the
    workload's own oracle (workloads/: a static 2-shard BSP cluster
    run for MF, the StreamingDriver for PA's bitwise bar, a pure-numpy
    bincount for the sketch's integer counts).  Cached per workload
    shape."""
    key = (s.workload, s.rounds, s.batch, s.num_users, s.num_items,
           s.dim, s.num_workers)
    cached = _ORACLE_CACHE.get(key)
    if cached is not None:
        return cached
    values = np.asarray(_make_workload(s).oracle_values())
    _ORACLE_CACHE[key] = values
    return values


def _build_driver(s: Scenario, workload, wal_dir: str, registry):
    common = dict(
        num_shards=s.num_shards,
        num_workers=s.num_workers,
        staleness_bound=s.staleness_bound,
        wal_dir=wal_dir,
        wire_format=s.wire_format,
        request_timeout=s.request_timeout,
        retry_timeout=s.retry_timeout,
        connect_timeout=2.0,
    )
    if s.adaptive:
        # the straggler-adaptive kill switch (adaptive/): AdaptiveClock
        # with the derived ceiling, plus hedged pushes — safe on the
        # elastic drivers because membership-backed pushes carry a pid
        common.update(
            adaptive=True,
            adaptive_push_hedge_after_s=0.05,
        )
    if s.tiered:
        # the two-tier store (tierstore/): hot tier deliberately
        # smaller than the slice, so the schedule's recovery paths
        # must cross the mmap cold slab
        common.update(
            store_backend="tiered",
            tier_hot_rows=s.tier_hot_rows,
        )
    if s.replicated:
        cfg = ReplicatedClusterConfig(replication_factor=1, **common)
        cls = NemesisReplicatedDriver
    else:
        cfg = ElasticClusterConfig(**common)
        cls = NemesisElasticDriver
    from ..workloads import build_cluster_driver

    return build_cluster_driver(
        workload, config=cfg, driver_cls=cls, registry=registry,
        driver_kwargs={"nemesis_seed": s.seed},
    )


# ---------------------------------------------------------------------------
# op execution
# ---------------------------------------------------------------------------


def _corrupt_row(driver, gid: int) -> None:
    """The seeded SILENT violation: perturb one stored row out-of-band
    (no WAL record, no ledger entry — simulated bit-rot).  Only the
    final-table parity checker can see it; that is the point."""
    import jax.numpy as jnp

    from ..core.store import ShardedParamStore

    owner = int(driver.partitioner.shard_of(np.asarray([gid]))[0])
    sh = driver.shards[owner]
    with sh._lock:
        mirror = np.array(sh.store.values())
        local = sh.partitioner.to_local(
            sh.shard_id, np.asarray([gid], np.int64)
        )
        mirror[local] += 1.0
        sh.store = ShardedParamStore.from_values(jnp.asarray(mirror))
        sh._host_mirror = None


def _execute_op(driver, op: NemesisOp) -> None:
    a = op.action
    if a in ("scale_out", "scale_in", "sleep", "corrupt_row",
             "kill_shard", "replace_shard", "promote_shard"):
        if a == "kill_shard":
            driver.kill_shard(op.shard)
        elif a == "replace_shard":
            driver.replace_shard(op.shard)
        elif a == "promote_shard":
            driver.promote_shard(op.shard)
        elif a == "scale_out":
            driver.scale_out(op.count)
        elif a == "scale_in":
            driver.scale_in(op.count)
        elif a == "sleep":
            time.sleep(op.ms / 1e3)
        else:
            _corrupt_row(driver, op.gid)
        return
    proxy = driver.mesh.get(op.shard)
    if proxy is None:
        raise RuntimeError(f"no mesh proxy for shard {op.shard}")
    if a == "partition":
        proxy.partition(
            op.mode, duration_s=(op.ms / 1e3) if op.ms > 0 else None
        )
    elif a == "heal":
        proxy.heal()
    elif a == "delay":
        proxy.set_delay(op.ms, op.jitter_ms, op.mode)
    elif a == "clear_delay":
        proxy.clear_delay()
    elif a == "drip":
        proxy.set_drip(op.bytes_per_sec, op.mode)
    elif a == "clear_drip":
        proxy.clear_drip()
    elif a in ("truncate_next", "dup_next", "reorder_next"):
        direction = op.mode if op.mode != "both" else "s2c"
        kind = {
            "truncate_next": "truncate_rst",
            "dup_next": "dup",
            "reorder_next": "reorder",
        }[a]
        proxy.inject_once(
            kind, direction, keep_frac=op.keep_frac, count=op.count,
            cut=op.cut,
        )
    elif a == "half_open":
        proxy.half_open(op.count)
    else:  # pragma: no cover — scenarios.py validates the vocabulary
        raise ValueError(f"unknown op action {a!r}")


# ---------------------------------------------------------------------------
# the scenario executor
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScenarioReport:
    """One scenario's full outcome — the Jepsen-style verdict table."""

    scenario: Scenario
    ok: bool                      # every invariant checker passed
    verdicts: List[Verdict]
    faults: Dict[str, int]        # injected, per class
    rounds: int
    wall_s: float
    ops_executed: int
    ops_skipped: int
    schedule_json: str
    artifacts: List[str]

    @property
    def as_expected(self) -> bool:
        """Did the run match the scenario's recorded expectation?
        (``pass`` scenarios must be ok; ``violation`` scenarios must be
        caught, i.e. NOT ok.)"""
        return self.ok == (self.scenario.expect == "pass")

    def as_dict(self) -> dict:
        return {
            "name": self.scenario.name,
            "seed": self.scenario.seed,
            "expect": self.scenario.expect,
            "ok": self.ok,
            "as_expected": self.as_expected,
            "verdicts": [v.as_dict() for v in self.verdicts],
            "faults": dict(sorted(self.faults.items())),
            "rounds": self.rounds,
            "wall_s": round(self.wall_s, 3),
            "ops_executed": self.ops_executed,
            "ops_skipped": self.ops_skipped,
        }


def run_scenario(
    scenario: Scenario,
    *,
    wal_root: str,
    registry: Optional[MetricsRegistry] = None,
    witness: bool = False,
    artifact_dir: Optional[str] = None,
    serving_budget: int = 0,
    timeline=None,
) -> ScenarioReport:
    """Execute one scenario end to end and check every invariant.

    ``wal_root`` hosts a FRESH per-run WAL directory (a stale log
    would replay a previous run's updates into this one).
    ``witness=True`` wraps the whole topology in the lockwitness
    capture (measurably slower; the battery runs one witnessed
    scenario, not all).  ``artifact_dir`` enables failure artifacts:
    the flight-recorder blackbox and the canonical schedule JSON.
    ``timeline`` is an optional (not-yet-started)
    :class:`~..telemetry.timeline.TimelineRecorder` built over the
    SAME registry: it samples for the duration of the run and every
    executed nemesis op is ``mark()``-ed onto its time axis, so
    detector firings can be cross-referenced against fault onset (the
    detection A/B in benchmarks/timeline_detection_ab.py)."""
    reg = registry if registry is not None else MetricsRegistry()
    t0 = time.perf_counter()
    workload = _make_workload(scenario)
    oracle = oracle_values(scenario) if scenario.parity else None
    batches = workload.batches()
    wal_dir = tempfile.mkdtemp(prefix=f"{scenario.name}-", dir=wal_root)
    ledger = ThreadLedger()

    rec = None
    prev_rec = flightrec.get_recorder()
    if artifact_dir is not None:
        rec = flightrec.FlightRecorder(
            registry=reg, results_dir=artifact_dir,
            min_dump_interval_s=0.0,
        )
        rec.note("scenario_start", name=scenario.name, seed=scenario.seed)
    flightrec.set_recorder(rec)
    if timeline is not None:
        timeline.mark(
            "scenario_start", name=scenario.name, seed=scenario.seed
        )
        timeline.start()

    errors: List[str] = []
    served = [0]
    read_errors = [0]
    reader_cache_stats: dict = {}
    progress = {"round": -1, "done": False}
    cond = threading.Condition()
    ops_executed = [0]
    ops_skipped = [0]
    values: Optional[np.ndarray] = None
    acked = applied = 0
    rounds_done = 0
    samples: List[int] = []
    bound_samples: List[List[int]] = []
    tier_samples: List[dict] = []
    adaptive_rt = None
    adaptive_tl = None
    faults: Dict[str, int] = {}
    inversions: list = []

    if witness:
        from ..telemetry import lockwitness

        capture_cm = lockwitness.capture()
    else:
        capture_cm = contextlib.nullcontext()

    try:
        with capture_cm as w:
            driver = _build_driver(scenario, workload, wal_dir, reg)
            driver.start()
            if scenario.adaptive:
                # detection → control: a worker-entity SkewTracker over
                # the per-worker pull RTT histograms feeds the
                # AdaptiveRuntime, which drives the driver's
                # AdaptiveClock allowances through the storm
                from ..adaptive.controller import AdaptiveRuntime
                from ..telemetry.timeline import (
                    SkewTracker, TimelineRecorder,
                )

                adaptive_tl = TimelineRecorder(
                    reg, interval_s=0.05,
                    include=lambda n: n == "cluster_pull_rtt_seconds",
                    skew=[SkewTracker(
                        "cluster_pull_rtt_seconds",
                        entity_label="worker", field="p50",
                        min_points=2, warmup_evals=2,
                    )],
                ).start()
                adaptive_rt = AdaptiveRuntime(
                    driver, adaptive_tl, interval_s=0.05, registry=reg,
                ).start()

            def round_hook(worker: int, rnd: int) -> None:
                with cond:
                    if rnd > progress["round"]:
                        progress["round"] = rnd
                        cond.notify_all()

            def op_loop() -> None:
                for op in scenario.ops:
                    with cond:
                        cond.wait_for(
                            lambda: progress["round"] >= op.at_round
                            or progress["done"],
                            timeout=120,
                        )
                        if progress["done"] and (
                            progress["round"] < op.at_round
                        ):
                            ops_skipped[0] += 1
                            continue
                    if rec is not None:
                        rec.note(
                            "nemesis_op", action=op.action,
                            shard=op.shard, at_round=op.at_round,
                        )
                    if timeline is not None:
                        timeline.mark(
                            "nemesis_op", action=op.action,
                            shard=op.shard, at_round=op.at_round,
                        )
                    try:
                        _execute_op(driver, op)
                        ops_executed[0] += 1
                    except Exception as e:  # noqa: BLE001 — verdicted
                        errors.append(
                            f"op {op.action}@r{op.at_round}: "
                            f"{type(e).__name__}: {e}"
                        )
                # settle: nothing stays armed past the schedule
                for proxy in driver.mesh.values():
                    proxy.heal()
                    proxy.clear_delay()
                    proxy.clear_drip()

            stop_reader = threading.Event()

            def reader_loop() -> None:
                client = driver._make_client(worker="nemesis-reader")
                ids = np.arange(
                    min(8, workload.capacity), dtype=np.int64
                )
                # workload serving probes (predict / query / topk —
                # workloads/serving.py handlers, minus the socket):
                # the error budget covers the workload's own verbs
                # through the fault window, not just raw pulls
                probe_rng = np.random.default_rng(scenario.seed + 17)
                has_probes = bool(workload.serving_verbs)
                cache = None
                if scenario.hotcache:
                    # the cached serving reader (hotcache/): every read
                    # id is leaseable, bound enforced client-side — the
                    # lease_staleness verdict audits what it served
                    from ..hotcache import HotRowCache, StaticHotSet

                    cache = HotRowCache(
                        HOTCACHE_READER_BOUND, capacity=64,
                        registry=reg, worker="nemesis-reader",
                    )
                    client.attach_hotcache(
                        cache, StaticHotSet(ids), lease_ttl=8
                    )
                try:
                    while not stop_reader.is_set():
                        try:
                            client.pull_batch(ids)
                            served[0] += 1
                        except Exception:  # noqa: BLE001 — budgeted
                            read_errors[0] += 1
                        if has_probes:
                            probe = workload.probe_request(probe_rng)
                            if probe is not None:
                                try:
                                    workload.serve(client, *probe)
                                    served[0] += 1
                                except Exception:  # noqa: BLE001
                                    read_errors[0] += 1
                        stop_reader.wait(0.004)
                finally:
                    if cache is not None:
                        reader_cache_stats.update(cache.stats())
                    client.close()

            op_thread = threading.Thread(
                target=op_loop, name="nemesis-ops", daemon=True
            )
            op_thread.start()
            reader = None
            if scenario.serving_reads:
                reader = threading.Thread(
                    target=reader_loop, name="nemesis-reader-loop",
                    daemon=True,
                )
                reader.start()
            try:
                with StalenessSampler(driver) as sampler, \
                        AdaptiveBoundSampler(driver) as bsampler, \
                        TierResidencySampler() as tsampler:
                    try:
                        result = driver.run(
                            batches, round_hook=round_hook, timeout=180
                        )
                        values = result.values
                        rounds_done = result.rounds
                    except BaseException as e:  # noqa: BLE001 — verdicted
                        errors.append(
                            f"run: {type(e).__name__}: {e}"
                        )
                samples = list(sampler.samples)
                bound_samples = list(bsampler.samples)
                tier_samples = list(tsampler.samples)
            finally:
                with cond:
                    progress["done"] = True
                    cond.notify_all()
                op_thread.join(timeout=30)
                stop_reader.set()
                if reader is not None:
                    reader.join(timeout=30)
                # the audit counters live on objects stop() clears
                acked = sum(c.rows_pushed for c in driver._clients)
                applied = sum(
                    sh.rows_applied for sh in driver.all_shards
                )
                faults = driver.faults_injected()
                if adaptive_rt is not None:
                    adaptive_rt.stop()
                if adaptive_tl is not None:
                    adaptive_tl.stop()
                driver.stop()
        if witness:
            inversions = list(w.inversions)
    finally:
        if timeline is not None:
            timeline.sample()  # one final tick: the post-run state
            timeline.stop()
            timeline.mark("scenario_end", name=scenario.name)
        flightrec.set_recorder(prev_rec)

    # under the adaptive runtime, widened allowances legally raise the
    # live spread up to the CEILING (+1 round in flight) — the stock
    # bound would false-positive on exactly the behaviour the runtime
    # exists to produce; the ceiling derivation mirrors _make_clock
    bound = scenario.staleness_bound
    ceiling = (
        2 * bound + 1
        if scenario.adaptive and bound is not None else bound
    )
    verdicts = [
        check_no_errors(errors),
        check_exactly_once(acked, applied),
        check_staleness(samples, ceiling),
    ]
    if scenario.adaptive:
        verdicts.append(
            check_adaptive_bound(bound_samples, bound, ceiling)
        )
    if scenario.tiered:
        verdicts.append(check_tier_residency(tier_samples))
    if scenario.parity:
        if values is None:
            verdicts.append(Verdict(
                "final_table_parity", False, "run produced no table"
            ))
        else:
            # the workload declares its own parity bar (workloads/):
            # allclose fp32 for MF, bitwise for PA, integer-exact for
            # sketches
            verdicts.append(workload.parity_verdict(values, oracle))
    if scenario.serving_reads:
        verdicts.append(check_serving_budget(
            served[0], read_errors[0], budget=serving_budget
        ))
    if scenario.hotcache:
        verdicts.append(check_lease_staleness(
            reader_cache_stats, bound=HOTCACHE_READER_BOUND
        ))
    if witness:
        verdicts.append(check_lock_inversions(inversions))
    verdicts.append(ledger.check())

    ok = all(v.ok for v in verdicts)
    artifacts: List[str] = []
    if not ok and artifact_dir is not None:
        os.makedirs(artifact_dir, exist_ok=True)
        sched_path = os.path.join(
            artifact_dir, f"nemesis_schedule_{scenario.name}.json"
        )
        with open(sched_path, "w") as f:
            f.write(scenario.to_json() + "\n")
        artifacts.append(sched_path)
        if rec is not None:
            for v in verdicts:
                if not v.ok:
                    rec.note("invariant_violated", name=v.name,
                             detail=v.detail)
            path = rec.dump(f"nemesis_{scenario.name}", force=True)
            if path:
                artifacts.append(path)
    return ScenarioReport(
        scenario=scenario,
        ok=ok,
        verdicts=verdicts,
        faults=faults,
        rounds=rounds_done,
        wall_s=time.perf_counter() - t0,
        ops_executed=ops_executed[0],
        ops_skipped=ops_skipped[0],
        schedule_json=scenario.to_json(),
        artifacts=artifacts,
    )


# ---------------------------------------------------------------------------
# randomized search + shrinker
# ---------------------------------------------------------------------------


def search_scenarios(
    seeds, *, wal_root: str, artifact_dir: Optional[str] = None, **overrides
) -> Tuple[List[ScenarioReport], List[ScenarioReport]]:
    """Run one sampled scenario per seed; returns ``(passed, failed)``.
    Every failure is reproducible from its seed alone
    (``Scenario.from_seed(seed)`` regenerates the schedule) and carries
    the schedule JSON + flight-recorder artifact when ``artifact_dir``
    is set."""
    passed: List[ScenarioReport] = []
    failed: List[ScenarioReport] = []
    for seed in seeds:
        s = Scenario.from_seed(int(seed), **overrides)
        report = run_scenario(
            s, wal_root=wal_root, artifact_dir=artifact_dir
        )
        (passed if report.ok else failed).append(report)
    return passed, failed


def shrink(
    scenario: Scenario,
    fails: Callable[[Scenario], bool],
    *,
    max_runs: int = 24,
) -> Tuple[Scenario, int]:
    """Minimize a failing schedule: greedily drop ops while ``fails``
    still holds (delta debugging, one-op granularity — schedules are
    short).  Returns ``(minimized, runs_used)``; the minimized
    scenario still fails and every remaining op is load-bearing
    (removing any one of them was tried and made the failure
    disappear, or the run budget ran out first)."""
    ops = list(scenario.ops)
    runs = 0
    changed = True
    while changed and len(ops) > 1:
        changed = False
        for i in range(len(ops)):
            if runs >= max_runs:
                return scenario.with_ops(ops), runs
            candidate = scenario.with_ops(ops[:i] + ops[i + 1:])
            runs += 1
            if fails(candidate):
                ops.pop(i)
                changed = True
                break
    return scenario.with_ops(ops), runs


# ---------------------------------------------------------------------------
# the regression corpus
# ---------------------------------------------------------------------------


def write_corpus(
    scenarios=None, *, directory: str = CORPUS_DIR
) -> List[str]:
    """Serialize schedules into the committed corpus (canonical JSON,
    one file per scenario)."""
    if scenarios is None:
        scenarios = list(BUILTIN_SCENARIOS) + [VIOLATION_SCENARIO]
    os.makedirs(directory, exist_ok=True)
    paths = []
    for s in scenarios:
        path = os.path.join(directory, f"{s.name}.json")
        with open(path, "w") as f:
            f.write(s.to_json() + "\n")
        paths.append(path)
    return paths


def load_corpus(directory: str = CORPUS_DIR) -> List[Scenario]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(directory, name)) as f:
            out.append(Scenario.from_json(f.read().strip()))
    return out


def replay_corpus(
    *,
    wal_root: str,
    directory: str = CORPUS_DIR,
    artifact_dir: Optional[str] = None,
    witness_scenario: Optional[str] = "two_way_partition_heal",
) -> List[ScenarioReport]:
    """Replay every committed schedule and check its recorded
    expectation (the tier-1 regression gate).  One scenario runs under
    the lockwitness capture (``witness_scenario``); raising it to all
    scenarios is correct but slow.  Raises ``AssertionError`` naming
    every scenario whose outcome no longer matches."""
    reports = []
    for s in load_corpus(directory):
        reports.append(run_scenario(
            s, wal_root=wal_root, artifact_dir=artifact_dir,
            witness=(s.name == witness_scenario),
        ))
    mismatched = [r for r in reports if not r.as_expected]
    if mismatched:
        lines = []
        for r in mismatched:
            bad = [v for v in r.verdicts if not v.ok]
            lines.append(
                f"{r.scenario.name} (expect={r.scenario.expect}, "
                f"ok={r.ok}): "
                + ("; ".join(f"{v.name}: {v.detail}" for v in bad)
                   if bad else "unexpectedly clean")
            )
        raise AssertionError(
            "corpus replay mismatched expectations:\n" + "\n".join(lines)
        )
    return reports


__all__ = [
    "CORPUS_DIR",
    "NemesisElasticDriver",
    "NemesisReplicatedDriver",
    "ScenarioReport",
    "load_corpus",
    "oracle_values",
    "replay_corpus",
    "run_scenario",
    "search_scenarios",
    "shrink",
    "write_corpus",
]
