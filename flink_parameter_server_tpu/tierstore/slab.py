"""The cold tier: an mmap'd slab file of fixed-width fp32 rows.

A slab is a CACHE, not a log: rows land here only when a mutated row
is demoted from the hot tier (or assigned while cold), and a row that
was never written simply is not present — the caller recomputes it
from the deterministic init.  Losing the file therefore loses nothing
durable (WAL + checkpoint own durability), which is why the slab is
created unlinked-on-close in scratch space rather than alongside the
WAL.

Layout: ``slots × row_elems`` float32, grown by doubling via
``ftruncate`` + re-mmap.  The id→slot index is a plain int32 array
over the local id space (4 bytes/row — at the 2^24-row Criteo scale
that is 64 MiB, a fixed cost the recorded RSS bound budgets for; a
python dict of millions of resident entries would cost an order of
magnitude more and dominate lookup profiles).  Writes go through a
transient ``np.frombuffer`` view that is dropped before any resize so
``mmap`` never sees an exported buffer.
"""
from __future__ import annotations

import mmap
import os
import tempfile
from typing import Optional, Tuple

import numpy as np


class ColdSlab:
    """mmap-backed fixed-width row cache over a local id space of
    ``n_rows``.  Single-owner (the shard lock serializes callers) —
    no internal locking."""

    def __init__(
        self,
        n_rows: int,
        row_elems: int,
        *,
        dir: Optional[str] = None,
        initial_slots: int = 1024,
        name_hint: str = "slab",
    ):
        if n_rows < 1 or row_elems < 1:
            raise ValueError(
                f"n_rows={n_rows}, row_elems={row_elems}: need >= 1"
            )
        self.n_rows = int(n_rows)
        self.row_elems = int(row_elems)
        self.row_nbytes = self.row_elems * 4  # fp32
        # id -> slot (−1 = not cached).  int32 caps the slab at 2^31
        # slots, far beyond the mutated-row working sets this tier
        # exists for.
        self._slot_of = np.full(self.n_rows, -1, np.int32)
        self._free: list = []
        self._next_slot = 0
        self._slots = max(8, int(initial_slots))
        fd, self._path = tempfile.mkstemp(
            prefix=f"fps-tier-{name_hint}-", suffix=".slab", dir=dir
        )
        self._fd = fd
        os.ftruncate(fd, self._slots * self.row_nbytes)
        self._mm: Optional[mmap.mmap] = mmap.mmap(
            fd, self._slots * self.row_nbytes
        )
        self.rows_written = 0  # cumulative write calls' row count

    # -- introspection -----------------------------------------------------
    @property
    def path(self) -> str:
        return self._path

    @property
    def rows(self) -> int:
        """Rows currently cached."""
        return self._next_slot - len(self._free)

    @property
    def nbytes(self) -> int:
        """Slab file size (allocated, not just occupied)."""
        return self._slots * self.row_nbytes

    def contains(self, local_ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        return self._slot_of[ids] >= 0

    # -- data path ---------------------------------------------------------
    def _view(self) -> np.ndarray:
        # transient — callers must not retain it past the statement
        # (resize closes the mmap, which would raise BufferError on a
        # live export)
        return np.frombuffer(self._mm, np.float32).reshape(
            self._slots, self.row_elems
        )

    def _grow(self, need_slots: int) -> None:
        slots = self._slots
        while slots < need_slots:
            slots *= 2
        self._mm.close()
        os.ftruncate(self._fd, slots * self.row_nbytes)
        self._mm = mmap.mmap(self._fd, slots * self.row_nbytes)
        self._slots = slots

    def _alloc(self, n: int) -> np.ndarray:
        out = np.empty(n, np.int64)
        take = min(n, len(self._free))
        for i in range(take):
            out[i] = self._free.pop()
        fresh = n - take
        if fresh:
            if self._next_slot + fresh > self._slots:
                self._grow(self._next_slot + fresh)
            out[take:] = np.arange(
                self._next_slot, self._next_slot + fresh, dtype=np.int64
            )
            self._next_slot += fresh
        return out

    def write(self, local_ids: np.ndarray, rows: np.ndarray) -> None:
        """Upsert ``rows`` (``(n, row_elems)`` fp32) for unique
        ``local_ids``; ids already cached overwrite in place."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if ids.size == 0:
            return
        rows = np.ascontiguousarray(rows, np.float32).reshape(
            ids.size, self.row_elems
        )
        slots = self._slot_of[ids].astype(np.int64)
        fresh = slots < 0
        if fresh.any():
            new_slots = self._alloc(int(fresh.sum()))
            slots[fresh] = new_slots
            self._slot_of[ids[fresh]] = new_slots.astype(np.int32)
        self._view()[slots] = rows
        self.rows_written += ids.size

    def read(self, local_ids: np.ndarray) -> np.ndarray:
        """Rows for unique ``local_ids`` — every id must be cached
        (check :meth:`contains` first)."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        slots = self._slot_of[ids].astype(np.int64)
        if ids.size and slots.min() < 0:
            missing = ids[slots < 0]
            raise KeyError(
                f"slab read of {missing.size} uncached rows "
                f"(e.g. local id {int(missing[0])})"
            )
        return self._view()[slots].copy()

    def drop(self, local_ids: np.ndarray) -> int:
        """Forget cached rows (slots return to the free list);
        uncached ids are ignored.  Returns rows dropped."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        slots = self._slot_of[ids]
        held = slots >= 0
        if not held.any():
            return 0
        self._free.extend(slots[held].tolist())
        self._slot_of[ids[held]] = -1
        return int(held.sum())

    def close(self) -> None:
        if self._mm is None:
            return
        self._mm.close()
        self._mm = None
        os.close(self._fd)
        try:
            os.unlink(self._path)
        except OSError:
            pass


__all__ = ["ColdSlab"]
