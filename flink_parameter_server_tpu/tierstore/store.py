"""The two-tier store behind ``ParamShard(store_backend="tiered")``.

Contract (docs/tierstore.md): a row's current value is

  1. the HOT tier copy if the row is resident,
  2. else the slab (cold tier) copy if one exists,
  3. else ``row_init(local_id)`` — the deterministic per-id init.

Rule 3 is the recomputability rule that makes the whole design work:
an absent row is not a fault, so the cold tier only ever holds rows
whose value DIFFERS from init (mutated rows), and dropping a clean
hot row is free.  Durability still belongs to the WAL + checkpoint
planes — a shard restart builds a fresh empty store and WAL replay
repopulates the mutated set (touching the cold tier as it goes).

Admission is promote-on-access: a missed row becomes resident (it was
just paid for).  Eviction is where the hot-key sketches earn their
keep — when the free list runs dry a batch demotion scan (off the
per-request hot path, amortized) ranks unpinned residents by
(SpaceSaving membership, CountMin estimate) and demotes the coldest
down to the low-water mark; dirty victims are written to the slab,
clean victims are simply dropped.  Windowed decay halves both
sketches every ``decay_window`` observed ids so a popularity shift
demotes yesterday's celebrities.  Pinned rows (frozen for migration,
under lease — whatever ``pinned_fn`` reports) are never evicted.

Capacity is a target, not a wall: a batch larger than the hot tier
still gets correct service — rows that cannot be admitted are served
(and, when pushed, written) straight through to the slab and counted
as ``spills``.  The nemesis ``check_tier_residency`` invariant holds
resident ≤ capacity at every sample.

Single-owner under the shard lock, like ``_NumpyStore`` — no internal
locking.  fp32 only: the tiers must stay bitwise-comparable with the
jax/numpy dense backends (``verify_against_log`` promotes are audited
bitwise over ``values()``).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.hotkeys import CountMinSketch, SpaceSavingTopK
from .slab import ColdSlab

_SEED_CHUNK = 1 << 16


class TieredStore:
    """Hot-dense / cold-mmap row store over a local id space."""

    def __init__(
        self,
        n_rows: int,
        value_shape: Sequence[int] = (),
        *,
        row_init: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        hot_rows: int = 65536,
        slab_dir: Optional[str] = None,
        decay_window: int = 0,
        topk_capacity: int = 0,
        pinned_fn: Optional[Callable[[], np.ndarray]] = None,
        low_water: float = 0.9,
        name_hint: str = "shard",
    ):
        self.n_rows = int(n_rows)
        self.value_shape = tuple(int(s) for s in value_shape)
        self.row_elems = int(np.prod(self.value_shape, dtype=np.int64)) or 1
        self._row_init = row_init
        self.hot_rows = max(1, int(hot_rows))
        self._low_water = max(
            1, min(self.hot_rows, int(self.hot_rows * float(low_water)))
        )
        # hot tier: dense slots + both directions of the id<->slot map.
        # The id->slot index is a flat int32 array over the id space
        # (4 B/row) — see slab.py for the dict-vs-array tradeoff.
        self._hot = np.zeros((self.hot_rows, self.row_elems), np.float32)
        self._slot_of = np.full(self.n_rows, -1, np.int32)
        self._id_at = np.full(self.hot_rows, -1, np.int64)
        self._dirty = np.zeros(self.hot_rows, bool)
        self._free = np.arange(self.hot_rows - 1, -1, -1, np.int32)
        self._free_top = self.hot_rows
        self.slab = ColdSlab(
            self.n_rows, self.row_elems, dir=slab_dir, name_hint=name_hint
        )
        # admission/eviction analytics: raw CountMin + SpaceSaving from
        # telemetry/hotkeys.py with their own windowed decay (the tier
        # must track CURRENT popularity, not all-time)
        self.cms = CountMinSketch(
            width=max(2048, 2 * self.hot_rows // 4), depth=4, seed=7
        )
        self.topk = SpaceSavingTopK(
            capacity=int(topk_capacity) or max(8, min(1024, self.hot_rows))
        )
        self.decay_window = (
            int(decay_window) if decay_window else 8 * self.hot_rows
        )
        self._seen = 0
        # hot-path discipline (same as HotKeySketch.observe): a
        # gather/push only APPENDS its id batch; the unique/bincount/
        # dict sketch folding runs once per ~buffer ids.  The buffer
        # is a full hot-tier's worth of references so the fold is a
        # rare, batched event (p99, like an eviction scan) rather
        # than a per-batch tax on the median pull.  Eviction and
        # capacity-pressure admission flush first, so ranking always
        # reads the current window.
        self._obs_pending: list = []
        self._obs_n = 0
        self._obs_buffer = max(1 << 16, self.hot_rows)
        self._pinned_fn = pinned_fn
        # instruments (read by gauges / the `tiers` path)
        self.hits = 0
        self.misses = 0
        self.promotes = 0
        self.demotes = 0
        self.demote_writes = 0
        self.spills = 0
        self.evict_scans = 0
        self.last_scan_s = 0.0
        self.cum_scan_s = 0.0
        self.decays = 0
        self.pinned_last = 0

    # -- bookkeeping -------------------------------------------------------
    @property
    def resident(self) -> int:
        return self.hot_rows - self._free_top

    def _observe(self, ids: np.ndarray) -> None:
        self._obs_pending.append(ids)
        self._obs_n += ids.size
        if self._obs_n >= self._obs_buffer:
            self._flush_observed()

    def _flush_observed(self) -> None:
        """Fold the buffered id batches into both sketches (and run
        windowed decay).  Estimates are stale by at most one buffer
        between flushes — fine for an admission heuristic, and the
        amortization is what keeps the hit path near the dense
        store's fancy-index cost."""
        if not self._obs_pending:
            return
        ids = (
            self._obs_pending[0] if len(self._obs_pending) == 1
            else np.concatenate(self._obs_pending)
        )
        self._obs_pending = []
        self._obs_n = 0
        uniq, counts = np.unique(ids, return_counts=True)
        self.cms.add(uniq, counts)
        self.topk.update(uniq, counts, assume_unique=True)
        self._seen += ids.size
        if self._seen >= self.decay_window:
            self.cms.halve()
            self.topk.halve()
            self._seen = 0
            self.decays += 1

    def _pinned_slots(self) -> np.ndarray:
        """Hot slots of currently pinned rows (bool mask over slots)."""
        mask = np.zeros(self.hot_rows, bool)
        if self._pinned_fn is None:
            self.pinned_last = 0
            return mask
        pinned = np.asarray(self._pinned_fn(), np.int64).reshape(-1)
        if pinned.size:
            pinned = pinned[(pinned >= 0) & (pinned < self.n_rows)]
            slots = self._slot_of[pinned]
            slots = slots[slots >= 0]
            mask[slots] = True
        self.pinned_last = int(mask.sum())
        return mask

    def _evict(
        self, want: int, protect: Optional[np.ndarray] = None
    ) -> int:
        """Batch demotion: demote up to ``want`` residents, coldest
        first — non-top-K members before members, CountMin estimate
        ascending within each class; pinned rows are skipped, as are
        ``protect`` ids (the batch currently being served — evicting
        one mid-operation would invalidate its caller's slot map).
        Dirty victims are written to the slab; clean victims (hot
        copy == slab copy or == init) are dropped.  Returns slots
        freed."""
        self._flush_observed()
        t0 = time.perf_counter()
        occupied = self._id_at >= 0
        cand = occupied & ~self._pinned_slots()
        if protect is not None and protect.size:
            pslots = self._slot_of[protect]
            cand[pslots[pslots >= 0]] = False
        cand_slots = np.nonzero(cand)[0]
        freed = 0
        if cand_slots.size:
            cand_ids = self._id_at[cand_slots]
            tracked = np.fromiter(
                sorted(k for k, _, _ in self.topk.items()),
                np.int64,
            )
            if tracked.size:
                at = np.searchsorted(tracked, cand_ids)
                at[at == tracked.size] = 0
                member = tracked[at] == cand_ids
            else:
                member = np.zeros(cand_ids.size, bool)
            est = self.cms.estimate(cand_ids)
            # rank by (member, estimate) with a single int64 key and
            # an O(n) partial select — a full lexsort over the whole
            # resident set made each scan ~3x costlier
            key = est.astype(np.int64)
            key += member.astype(np.int64) * (int(key.max()) + 1)
            take = min(want, cand_slots.size)
            if take < cand_slots.size:
                order = np.argpartition(key, take - 1)[:take]
            else:
                order = np.arange(cand_slots.size)
            victims = cand_slots[order]
            dirty = self._dirty[victims]
            if dirty.any():
                dslots = victims[dirty]
                self.slab.write(self._id_at[dslots], self._hot[dslots])
                self.demote_writes += int(dirty.sum())
            self._slot_of[self._id_at[victims]] = -1
            self._id_at[victims] = -1
            self._dirty[victims] = False
            self._free[self._free_top: self._free_top + victims.size] = (
                victims.astype(np.int32)
            )
            self._free_top += victims.size
            freed = int(victims.size)
            self.demotes += freed
        self.evict_scans += 1
        self.last_scan_s = time.perf_counter() - t0
        self.cum_scan_s += self.last_scan_s
        return freed

    def _admit(
        self,
        ids: np.ndarray,
        rows: np.ndarray,
        *,
        dirty: bool,
        protect: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Make unique ``ids`` resident with values ``rows``; returns
        a bool mask of the ids actually admitted (the rest spilled —
        capacity exhausted by pinned rows or an oversized batch)."""
        k = ids.size
        if k > self._free_top:
            # demote down to the low-water mark in one scan so the
            # next few admissions stay off the eviction path
            want = max(k - self._free_top, self.resident - self._low_water)
            self._evict(int(want), protect=protect)
        take = min(k, self._free_top)
        admitted = np.zeros(k, bool)
        if take:
            if take < k:
                # capacity pressure: admit the hottest of the batch
                # (CountMin estimate), spill the rest
                self._flush_observed()
                order = np.argsort(
                    -self.cms.estimate(ids), kind="stable"
                )
                sel = order[:take]
            else:
                sel = np.arange(k)
            admitted[sel] = True
            slots = self._free[self._free_top - take: self._free_top]
            self._free_top -= take
            aid = ids[sel]
            self._hot[slots] = rows[sel]
            self._id_at[slots] = aid
            self._slot_of[aid] = slots
            self._dirty[slots] = dirty
            self.promotes += take
        return admitted

    def _fetch_cold(self, ids: np.ndarray) -> np.ndarray:
        """Values for unique non-resident ``ids``: slab copy if the
        row was ever demoted dirty, else the deterministic init."""
        rows = np.empty((ids.size, self.row_elems), np.float32)
        cached = self.slab.contains(ids)
        if cached.any():
            rows[cached] = self.slab.read(ids[cached])
        cold = ~cached
        if cold.any():
            cold_ids = ids[cold]
            if self._row_init is None:
                rows[cold] = 0.0
            else:
                rows[cold] = np.asarray(
                    self._row_init(cold_ids), np.float32
                ).reshape(cold_ids.size, self.row_elems)
        return rows

    # -- store surface (ParamShard-facing) ---------------------------------
    def gather(self, local_ids) -> np.ndarray:
        """Rows for ``local_ids`` (repeats allowed) as
        ``(n, *value_shape)`` fp32 — the pull/lease read path."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        slots = self._slot_of[ids]  # int32 — indexes _hot directly
        hit = slots >= 0
        n_hit = int(hit.sum())
        self.hits += n_hit
        # one full fancy-gather (miss lanes read slot 0 as a throwaway
        # and are overwritten below) — cheaper than a boolean-masked
        # gather + scatter pair on the all-hit common case
        out = self._hot[np.maximum(slots, 0)]
        if n_hit < ids.size:
            miss = ~hit
            miss_ids = np.unique(ids[miss])
            self.misses += ids.size - n_hit  # per reference, like hits
            rows = self._fetch_cold(miss_ids)
            admitted = self._admit(
                miss_ids, rows, dirty=False, protect=ids
            )
            if not admitted.all():
                self.spills += int((~admitted).sum())
            # serve from the fetched rows directly (admitted or not)
            pos = np.searchsorted(miss_ids, ids[miss])
            out[miss] = rows[pos]
        self._observe(ids)
        return out.reshape(ids.shape + self.value_shape)

    def push(self, local_ids, deltas) -> "TieredStore":
        """Scatter-add ``deltas`` (repeats accumulate); padding lanes
        (id −1) and out-of-range ids are dropped, matching the dense
        backends' sentinel routing."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        d = np.asarray(deltas, np.float32).reshape(
            ids.size, self.row_elems
        )
        ok = (ids >= 0) & (ids < self.n_rows)
        if not ok.all():
            ids, d = ids[ok], d[ok]
        if ids.size == 0:
            return self
        slots = self._slot_of[ids]
        miss = slots < 0
        self.hits += int((~miss).sum())
        if miss.any():
            miss_ids = np.unique(ids[miss])
            self.misses += int(miss.sum())  # per reference, like hits
            rows = self._fetch_cold(miss_ids)
            admitted = self._admit(
                miss_ids, rows, dirty=True, protect=ids
            )
            if not admitted.all():
                # write-through for rows the hot tier cannot take:
                # fold their deltas into the fetched values and spill
                # straight to the slab — correctness does not depend
                # on capacity
                cold_ids = miss_ids[~admitted]
                cold_rows = rows[~admitted]
                sel = np.isin(ids, cold_ids)
                pos = np.searchsorted(cold_ids, ids[sel])
                np.add.at(cold_rows, pos, d[sel])
                self.slab.write(cold_ids, cold_rows)
                self.spills += int(cold_ids.size)
                ids, d = ids[~sel], d[~sel]
            slots = self._slot_of[ids]
        if ids.size:
            np.add.at(self._hot, slots, d)
            self._dirty[slots] = True
            self._observe(ids)
        return self

    def assign(self, local_ids, values) -> None:
        """Overwrite rows (the migration ``load`` path).  Resident
        rows update in place (and become dirty); cold rows write
        straight to the slab — bulk loads must not thrash the hot
        tier."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        rows = np.asarray(values, np.float32).reshape(
            ids.size, self.row_elems
        )
        slots = self._slot_of[ids]
        res = slots >= 0
        if res.any():
            self._hot[slots[res]] = rows[res]
            self._dirty[slots[res]] = True
        cold = ~res
        if cold.any():
            self.slab.write(ids[cold], rows[cold])

    def values(self) -> np.ndarray:
        """Dense materialisation of the whole local slice — init
        overlaid with slab then hot.  O(n_rows): the checkpoint /
        ``verify_against_log`` / epoch-install path, NOT a per-request
        surface (at Criteo scale this allocates the full table)."""
        out = np.empty((self.n_rows, self.row_elems), np.float32)
        if self._row_init is None:
            out[:] = 0.0
        else:
            for lo in range(0, self.n_rows, _SEED_CHUNK):
                hi = min(lo + _SEED_CHUNK, self.n_rows)
                chunk = np.arange(lo, hi, dtype=np.int64)
                out[lo:hi] = np.asarray(
                    self._row_init(chunk), np.float32
                ).reshape(hi - lo, self.row_elems)
        cached = np.nonzero(self.slab._slot_of >= 0)[0].astype(np.int64)
        for lo in range(0, cached.size, _SEED_CHUNK):
            ids = cached[lo: lo + _SEED_CHUNK]
            out[ids] = self.slab.read(ids)
        occ = np.nonzero(self._id_at >= 0)[0]
        if occ.size:
            out[self._id_at[occ]] = self._hot[occ]
        return out.reshape((self.n_rows,) + self.value_shape)

    def seed_dense(self, values: np.ndarray) -> None:
        """Seed from a dense table (snapshot restore / epoch install):
        only rows that DIFFER from the deterministic init are written
        to the slab — rows equal to init stay absent (recomputable),
        so a mostly-init snapshot keeps the slab bounded."""
        rows = np.asarray(values, np.float32).reshape(
            self.n_rows, self.row_elems
        )
        for lo in range(0, self.n_rows, _SEED_CHUNK):
            hi = min(lo + _SEED_CHUNK, self.n_rows)
            chunk = np.arange(lo, hi, dtype=np.int64)
            if self._row_init is None:
                iv = np.zeros((hi - lo, self.row_elems), np.float32)
            else:
                iv = np.asarray(
                    self._row_init(chunk), np.float32
                ).reshape(hi - lo, self.row_elems)
            diff = np.nonzero((rows[lo:hi] != iv).any(axis=1))[0]
            if diff.size:
                self.slab.write(chunk[diff], rows[lo:hi][diff])

    # -- lifecycle / introspection -----------------------------------------
    def stats(self) -> dict:
        self._flush_observed()  # decay/sketch state current at scrape
        return {
            "resident_rows": int(self.resident),
            "hot_capacity_rows": int(self.hot_rows),
            "pinned_rows": int(self.pinned_last),
            "slab_rows": int(self.slab.rows),
            "slab_bytes": int(self.slab.nbytes),
            "hits": int(self.hits),
            "misses": int(self.misses),
            "promotes": int(self.promotes),
            "demotes": int(self.demotes),
            "demote_writes": int(self.demote_writes),
            "spills": int(self.spills),
            "evict_scans": int(self.evict_scans),
            "last_evict_scan_s": float(self.last_scan_s),
            "cum_evict_scan_s": float(self.cum_scan_s),
            "decays": int(self.decays),
        }

    def close(self) -> None:
        self.slab.close()


__all__ = ["TieredStore"]
