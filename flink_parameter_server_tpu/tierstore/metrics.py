"""``component=tierstore`` instruments + the ``tiers`` snapshot
registry.

Two consumers share one stats source (:meth:`TieredStore.stats`):

  * the metric plane — per-shard gauges registered on the shard's
    :class:`~..telemetry.registry.MetricsRegistry` (scraped as
    ``component=tierstore`` lines; see docs/tierstore.md's instrument
    catalog);
  * the ``tiers`` TelemetryServer path — ``psctl tiers`` wants the
    full per-shard stats dict, not flattened metric lines, so shards
    also register a snapshot callable here (process-wide, like
    :class:`~..telemetry.hotkeys.HotKeyAggregator`).  The callable is
    expected to take the shard lock itself; ``tiers_snapshot``
    returns ``None`` until the first store registers, which the
    exporter renders as the "no tiered shards" null payload.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_stores: Dict[str, Callable[[], dict]] = {}


def register_store(label: str, stats_fn: Callable[[], dict]) -> None:
    """Expose a tiered shard's stats under ``label`` (``shard-N`` /
    ``shard-N-fK`` for followers).  Last registration wins — a shard
    restart re-registers over its dead predecessor."""
    with _lock:
        _stores[str(label)] = stats_fn


def unregister_store(label: str) -> None:
    with _lock:
        _stores.pop(str(label), None)


def tiers_snapshot() -> Optional[Dict[str, dict]]:
    """``{label: stats_dict}`` for every registered tiered store, or
    ``None`` when no tiered shard ever registered (the cluster is not
    running ``store_backend="tiered"``)."""
    with _lock:
        if not _stores:
            return None
        fns = list(_stores.items())
    out: Dict[str, dict] = {}
    for label, fn in fns:
        try:
            st = fn()
        except Exception:
            # a shard mid-crash/restart must not poison the scrape
            continue
        if st is not None:
            out[label] = st
    return out


def clear() -> None:
    """Test hook: forget every registration."""
    with _lock:
        _stores.clear()


def register_instruments(reg, shard_label: str, stats_fn) -> None:
    """Register the per-shard gauge set on ``reg``.  Monotonic counts
    (hits/misses/promotes/demotes/spills) are exported as fn-backed
    gauges reading the store's cumulative counters — same pattern as
    ``cluster_shard_queue_depth``.  Registrations are literal (one
    call per instrument) so the fpsanalyze D002 catalog reconciliation
    can see every name — keep this list in lockstep with the
    docs/tierstore.md instrument table."""
    def field(name):
        def read():
            st = stats_fn()
            return None if st is None else st.get(name)

        return read

    shard = str(shard_label)
    reg.gauge("tier_resident_rows", component="tierstore",
              shard=shard, fn=field("resident_rows"))
    reg.gauge("tier_hot_capacity_rows", component="tierstore",
              shard=shard, fn=field("hot_capacity_rows"))
    reg.gauge("tier_pinned_rows", component="tierstore",
              shard=shard, fn=field("pinned_rows"))
    reg.gauge("tier_slab_rows", component="tierstore",
              shard=shard, fn=field("slab_rows"))
    reg.gauge("tier_slab_bytes", component="tierstore",
              shard=shard, fn=field("slab_bytes"))
    reg.gauge("tier_hits_total", component="tierstore",
              shard=shard, fn=field("hits"))
    reg.gauge("tier_misses_total", component="tierstore",
              shard=shard, fn=field("misses"))
    reg.gauge("tier_promotes_total", component="tierstore",
              shard=shard, fn=field("promotes"))
    reg.gauge("tier_demotes_total", component="tierstore",
              shard=shard, fn=field("demotes"))
    reg.gauge("tier_spills_total", component="tierstore",
              shard=shard, fn=field("spills"))
    reg.gauge("tier_evict_scan_seconds", component="tierstore",
              shard=shard, fn=field("last_evict_scan_s"))


__all__ = [
    "register_store",
    "unregister_store",
    "tiers_snapshot",
    "register_instruments",
    "clear",
]
