"""tierstore/ — two-tier ParamShard store (``store_backend="tiered"``).

Hot rows live dense in memory; cold rows live in an mmap'd slab file.
Because every row is recomputable from the deterministic per-id init
(:mod:`~..utils.initializers`), an ABSENT row is not a fault — the
cold tier is a cache of MUTATED rows only, and the WAL + checkpoint
planes remain the sole durability story (docs/tierstore.md).

  * :class:`~.slab.ColdSlab` — the mmap'd fixed-width row file plus
    its id→slot index and free list;
  * :class:`~.store.TieredStore` — the store surface
    :class:`~..cluster.shard.ParamShard` drives (``gather`` / ``push``
    / ``assign`` / ``values``), with CountMin + SpaceSaving admission
    ordering, windowed decay, pinned-row protection and batch
    demotion off the hot path;
  * :mod:`~.metrics` — ``component=tierstore`` instruments and the
    process-wide store registry behind the TelemetryServer ``tiers``
    path (``psctl tiers``).
"""
from .slab import ColdSlab
from .store import TieredStore
from .metrics import register_store, unregister_store, tiers_snapshot

__all__ = [
    "ColdSlab",
    "TieredStore",
    "register_store",
    "unregister_store",
    "tiers_snapshot",
]
