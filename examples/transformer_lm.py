"""Data-parallel Transformer LM through the dense PS (BASELINE config #5),
with optional sequence (ring attention) + tensor parallelism.

Run on the 8-device CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_lm.py
"""
import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_parameter_server_tpu.core.dense import (
    DenseParameterServer,
    transform_dense,
)
from flink_parameter_server_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    lm_loss,
)


def bigram_batches(n, B, T, vocab, seed=0):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(vocab)
    for _ in range(n):
        toks = np.empty((B, T), np.int32)
        toks[:, 0] = rng.integers(0, vocab, B)
        for t in range(1, T):
            toks[:, t] = perm[toks[:, t - 1]]
        yield {"tokens": toks}


def main():
    devices = jax.devices()
    mesh = None
    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_heads=8, n_layers=4, d_ff=512,
        max_seq=64, dtype=jnp.float32,
    )
    batch_sharding = None
    if len(devices) >= 8:
        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = TransformerConfig(
            vocab_size=256, d_model=128, n_heads=8, n_layers=4, d_ff=512,
            max_seq=64, dtype=jnp.float32,
            use_ring_attention=True, sp_axis="sp", tp_axis="tp",
        )
        batch_sharding = NamedSharding(mesh, P("dp", "sp"))

    params = init_params(jax.random.PRNGKey(0), cfg, mesh)
    server = DenseParameterServer(params, optax.adamw(3e-3))
    losses = []
    transform_dense(
        bigram_batches(80, B=8, T=64, vocab=256),
        lambda p, b: lm_loss(p, b, cfg, mesh=mesh),
        server,
        batch_sharding=batch_sharding,
        on_step=lambda i, l: losses.append(float(l)),
    )
    print(f"mesh={'dp2,sp2,tp2 + ring attention' if mesh else 'single device'}")
    print(f"loss {losses[0]:.3f} -> {np.mean(losses[-5:]):.3f} "
          f"(random = {np.log(256):.3f})")


if __name__ == "__main__":
    main()
