"""Data-parallel Transformer LM through the dense PS (BASELINE config #5),
showcasing every parallelism axis the framework supports.

Usage (ParameterTool-style args):
    python examples/transformer_lm.py [--mode sp|pp|ep|single]
        [--steps 80] [--remat]

Modes (with ≥8 devices):
    sp     dp×sp×tp mesh, ring attention          (default)
    pp     dp×pp mesh, GPipe pipelined layer stack
    ep     dp×ep mesh, switch-MoE expert parallelism
    single one device, dense

Run on the 8-device CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_lm.py --mode ep
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_parameter_server_tpu.core.dense import (
    DenseParameterServer,
    transform_dense,
)
from flink_parameter_server_tpu.models.transformer import (
    TransformerConfig,
    forward_pipelined,
    init_params,
    lm_loss,
    next_token_xent,
)
from flink_parameter_server_tpu.utils.config import Parameters


def bigram_batches(n, B, T, vocab, seed=0):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(vocab)
    for _ in range(n):
        toks = np.empty((B, T), np.int32)
        toks[:, 0] = rng.integers(0, vocab, B)
        for t in range(1, T):
            toks[:, t] = perm[toks[:, t - 1]]
        yield {"tokens": toks}


def main():
    params = Parameters.from_env().merged_with(
        Parameters.from_args(sys.argv[1:])
    )
    mode = params.get("mode", "sp")
    if mode not in ("sp", "pp", "ep", "single"):
        raise SystemExit(f"--mode {mode!r}: use one of sp, pp, ep, single")
    steps = params.get_int("steps", 80)
    remat = params.get_bool("remat")
    devices = jax.devices()
    if len(devices) < 8 and mode != "single":
        print(f"only {len(devices)} devices; falling back to --mode single")
        mode = "single"

    base = dict(
        vocab_size=256, d_model=128, n_heads=8, n_layers=4, d_ff=512,
        max_seq=64, dtype=jnp.float32, remat=remat,
    )
    mesh = None
    batch_sharding = None
    loss_fn = None

    if mode == "sp":
        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = TransformerConfig(
            **base, use_ring_attention=True, sp_axis="sp", tp_axis="tp"
        )
        batch_sharding = NamedSharding(mesh, P("dp", "sp"))
        loss_fn = lambda p, b: lm_loss(p, b, cfg, mesh=mesh)  # noqa: E731
    elif mode == "pp":
        mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "pp"))
        cfg = TransformerConfig(**base, pp_axis="pp")
        batch_sharding = NamedSharding(mesh, P("dp"))

        def loss_fn(p, b):
            logits = forward_pipelined(
                p, b["tokens"], cfg, mesh=mesh, num_microbatches=2
            )
            return next_token_xent(logits, b["tokens"])

    elif mode == "ep":
        mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "ep"))
        cfg = TransformerConfig(
            **base, num_experts=8, ep_axis="ep", moe_capacity=256
        )
        batch_sharding = NamedSharding(mesh, P("dp"))
        loss_fn = lambda p, b: lm_loss(p, b, cfg, mesh=mesh)  # noqa: E731
    else:  # "single" (validated above)
        cfg = TransformerConfig(**base)
        loss_fn = lambda p, b: lm_loss(p, b, cfg)  # noqa: E731

    model_params = init_params(jax.random.PRNGKey(0), cfg, mesh)
    server = DenseParameterServer(model_params, optax.adamw(3e-3))
    losses = []
    transform_dense(
        bigram_batches(steps, B=8, T=64, vocab=256),
        loss_fn,
        server,
        batch_sharding=batch_sharding,
        on_step=lambda i, l: losses.append(float(l)),
    )
    mesh_desc = dict(mesh.shape) if mesh is not None else "single device"
    print(f"mode={mode} mesh={mesh_desc} remat={remat}")
    print(f"loss {losses[0]:.3f} -> {np.mean(losses[-5:]):.3f} "
          f"(random = {np.log(256):.3f})")


if __name__ == "__main__":
    main()
