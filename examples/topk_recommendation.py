"""MF training + top-K recommendation serving.

Mirrors the reference's ``PSOnlineMatrixFactorizationAndTopK``
(SURVEY.md §2 #8): train online MF, then answer top-K item queries per
user — LEMP pruning replaced by exact MXU-matmul MIPS (`ops/topk.py`).
"""
import numpy as np
import jax.numpy as jnp

from flink_parameter_server_tpu.data.movielens import synthetic_ratings
from flink_parameter_server_tpu.data.streams import microbatches
from flink_parameter_server_tpu.models.matrix_factorization import ps_online_mf
from flink_parameter_server_tpu.models.topk_recommender import query_topk


def main():
    data = synthetic_ratings(500, 800, 60_000, rank=8, noise=0.02, seed=1)
    res = ps_online_mf(
        microbatches(data, 2048, epochs=4, shuffle_seed=0),
        num_users=500, num_items=800, dim=16, learning_rate=0.06,
        collect_outputs=False,
    )

    users = jnp.arange(5)
    # exclude each user's already-rated items (first 8 shown here)
    seen = np.full((5, 8), -1, np.int32)
    for u in range(5):
        items_u = data["item"][data["user"] == u][:8]
        seen[u, : len(items_u)] = items_u
    scores, ids = query_topk(
        res.store, res.worker_state, users, k=10, exclude=jnp.asarray(seen)
    )
    for u in range(5):
        print(f"user {u}: top-10 items {np.asarray(ids[u]).tolist()}")


if __name__ == "__main__":
    main()
