"""Migration story: an unmodified event-API logic on the device store.

Step 1 of a reference migration is usually "keep my WorkerLogic, move the
parameters": ``transform_hybrid`` runs the exact callback class you wrote
for the event backend against a ``ShardedParamStore`` — per chunk, every
pull becomes one deduped sharded gather and every push one scatter-add.

Usage:
    python examples/hybrid_migration.py [--chunk 512] [--epochs 5]
"""
import sys

import numpy as np

from flink_parameter_server_tpu import (
    ShardedParamStore,
    make_mesh,
    transform_hybrid,
)
from flink_parameter_server_tpu.data.movielens import synthetic_ratings
from flink_parameter_server_tpu.models.matrix_factorization import (
    MFWorkerLogic,
    SGDUpdater,
)
from flink_parameter_server_tpu.utils.config import Parameters
from flink_parameter_server_tpu.utils.initializers import ranged_random_factor


def main():
    params = Parameters.from_args(sys.argv[1:])
    chunk = params.get_int("chunk", 512)
    epochs = params.get_int("epochs", 5)

    num_users, num_items = 300, 400
    data = synthetic_ratings(num_users, num_items, 30_000, rank=4,
                             noise=0.05, seed=0)
    ratings = list(
        zip(data["user"].tolist(), data["item"].tolist(),
            data["rating"].tolist())
    )

    import jax

    # every device beyond the first becomes a ps shard: the point of the
    # demo is the SHARDED parameter plane under unchanged worker code
    mesh = make_mesh(1) if len(jax.devices()) > 1 else None

    # the SAME class that runs on the event backend — zero changes
    worker = MFWorkerLogic(dim=8, updater=SGDUpdater(0.1), seed=0)
    store = ShardedParamStore.create(
        num_items, (8,), init_fn=ranged_random_factor(1, (8,)), mesh=mesh
    )
    res = transform_hybrid(ratings * epochs, worker, store, chunk_size=chunk)

    item_f = np.asarray(res.store.values())
    user_f = np.zeros((num_users, 8), np.float32)
    for u, v in worker.user_vectors.items():
        user_f[u] = v
    pred = np.einsum(
        "ij,ij->i", user_f[data["user"]], item_f[data["item"]]
    )
    rmse = float(np.sqrt(np.mean((pred - data["rating"]) ** 2)))
    base = float(np.sqrt(np.mean(data["rating"] ** 2)))
    shards = mesh.shape["ps"] if mesh is not None else 1
    print(f"unmodified MFWorkerLogic on a {shards}-shard device store "
          f"(chunk={chunk}): rmse {rmse:.3f} vs zero-pred {base:.3f}")


if __name__ == "__main__":
    main()
