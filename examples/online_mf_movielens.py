"""Online matrix factorization — the framework's canonical example.

Mirrors the reference's ``PSOnlineMatrixFactorization`` demo job
(SURVEY.md §2 #7): stream ratings, keep user factors in worker state and
item factors on the sharded PS, train with async-style SGD.

Usage (ParameterTool-style args — utils/config.py):
    python examples/online_mf_movielens.py [--path ratings-file]
        [--dim 32] [--lr 0.05] [--epochs 3] [--batch 4096]
        [--scatter xla|pallas|xla_sorted] [--layout dense|packed|auto]
        [--presort 0|1] [--steps-per-call 1]

Without ``--path`` a synthetic Zipf-skewed MovieLens-like stream is used.
Runs on whatever devices are available (CPU mesh works:
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""
import sys

import numpy as np

from flink_parameter_server_tpu import make_mesh
from flink_parameter_server_tpu.data.movielens import (
    load_movielens,
    synthetic_ratings,
)
from flink_parameter_server_tpu.data.streams import microbatches
from flink_parameter_server_tpu.models.matrix_factorization import ps_online_mf
from flink_parameter_server_tpu.utils.config import Parameters


def main():
    params = Parameters.from_env().merged_with(
        Parameters.from_args(sys.argv[1:])
    )
    path = params.get("path")
    if path:
        data = load_movielens(path)
    else:
        data = synthetic_ratings(2000, 3000, 200_000, rank=8, seed=0)
    num_users = int(data["user"].max()) + 1
    num_items = int(data["item"].max()) + 1

    import jax

    mesh = None
    if len(jax.devices()) > 1:
        mesh = make_mesh()  # all devices on dp; ps=1

    res = ps_online_mf(
        microbatches(
            data,
            params.get_int("batch", 4096),
            epochs=params.get_int("epochs", 3),
            shuffle_seed=0,
        ),
        num_users=num_users,
        num_items=num_items,
        dim=params.get_int("dim", 32),
        learning_rate=params.get_float("lr", 0.05),
        mesh=mesh,
        collect_outputs=False,
        scatter_impl=params.get("scatter", "xla"),
        layout=params.get("layout", "dense"),
        presort=params.get_bool("presort", False),
        steps_per_call=params.get_int("steps-per-call", 1),
    )
    uf = np.asarray(res.worker_state)
    itf = np.asarray(res.store.values())
    pred = np.einsum("ij,ij->i", uf[data["user"]], itf[data["item"]])
    rmse = float(np.sqrt(np.mean((pred - data["rating"]) ** 2)))
    base = float(np.sqrt(np.mean(data["rating"] ** 2)))
    print(f"train RMSE {rmse:.4f} (zero-predictor {base:.4f})")
    print(f"user factors {uf.shape}, item factors {itf.shape}")


if __name__ == "__main__":
    main()
