"""Online matrix factorization — the framework's canonical example.

Mirrors the reference's ``PSOnlineMatrixFactorization`` demo job
(SURVEY.md §2 #7): stream ratings, keep user factors in worker state and
item factors on the sharded PS, train with async-style SGD.

Usage (ParameterTool-style args — utils/config.py):
    python examples/online_mf_movielens.py [--path ratings-file]
        [--socket host:port] [--num-users N] [--num-items M]
        [--dim 32] [--lr 0.05] [--epochs 3] [--batch 4096]
        [--scatter xla|pallas|xla_sorted] [--layout dense|packed|auto]
        [--presort 0|1] [--steps-per-call 1] [--chaos SEED]
        [--telemetry-port P]

``--telemetry-port P`` serves the unified metrics plane live while the
job trains (``telemetry/``, docs/observability.md): ``curl
http://127.0.0.1:P/metrics`` answers Prometheus text (step counters,
pull→push latency histogram, heartbeat ages), ``/healthz`` the
heartbeat view.  ``P=0`` binds an ephemeral port (printed at start).

``--chaos SEED`` demonstrates the resilience layer end to end: a
seeded FaultPlan crashes the job mid-training, and a RecoveringDriver
(checkpoints + update WAL under a temp workdir) restores, replays the
WAL tail and finishes the run — the printed factors match a
crash-free run bitwise.  See docs/resilience.md.

Without ``--path`` a synthetic Zipf-skewed MovieLens-like stream is used.
``--socket host:port`` instead trains from a LIVE newline-delimited
"user,item,rating" TCP stream until the producer closes — the
reference's canonical unbounded-source (socketTextStream) demo shape;
id spaces then come from --num-users/--num-items (the stream is
unbounded, so they cannot be inferred; combining --socket with the
bounded-file options --path/--epochs is an error).  On a multi-device mesh,
--num-users must be divisible by the dp size (worker state is
dp-sharded).
Runs on whatever devices are available (CPU mesh works:
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""
import sys

import numpy as np

from flink_parameter_server_tpu import make_mesh
from flink_parameter_server_tpu.data.movielens import (
    load_movielens,
    synthetic_ratings,
)
from flink_parameter_server_tpu.data.streams import microbatches
from flink_parameter_server_tpu.models.matrix_factorization import ps_online_mf
from flink_parameter_server_tpu.utils.config import Parameters


def _run_with_chaos(params, make_stream, *, num_users, num_items, mesh):
    """The --chaos path: same MF job, but supervised — a seeded fault
    plan crashes it mid-training and the RecoveringDriver brings it
    back via checkpoint + WAL replay (resilience/)."""
    import tempfile

    from flink_parameter_server_tpu.core.store import ShardedParamStore
    from flink_parameter_server_tpu.models.matrix_factorization import (
        OnlineMatrixFactorization,
        SGDUpdater,
    )
    from flink_parameter_server_tpu.resilience import (
        FaultPlan,
        RecoveringDriver,
        RestartPolicy,
    )
    from flink_parameter_server_tpu.training.driver import (
        DriverConfig,
        StreamingDriver,
    )
    from flink_parameter_server_tpu.utils.initializers import (
        ranged_random_factor,
    )

    seed = params.get_int("chaos", 0)
    logic = OnlineMatrixFactorization(
        num_users,
        params.get_int("dim", 32),
        updater=SGDUpdater(params.get_float("lr", 0.05)),
        mesh=mesh,
    )
    store = ShardedParamStore.create(
        num_items,
        (params.get_int("dim", 32),),
        init_fn=ranged_random_factor(1, (params.get_int("dim", 32),)),
        mesh=mesh,
        scatter_impl=params.get("scatter", "xla"),
        layout=params.get("layout", "dense"),
    )
    workdir = tempfile.mkdtemp(prefix="fps_chaos_demo_")
    driver = StreamingDriver(
        logic, store,
        config=DriverConfig(
            dump_model=False,
            checkpoint_every=params.get_int("checkpoint-every", 10),
            checkpoint_dir=f"{workdir}/ckpt",
            wal_dir=f"{workdir}/wal",
            presort=params.get_bool("presort", False),
            steps_per_call=params.get_int("steps-per-call", 1),
        ),
    )
    plan = FaultPlan.from_seed(
        seed, horizon=params.get_int("chaos-horizon", 40)
    )
    driver.add_group_hook(plan.driver_hook())
    rec = RecoveringDriver(
        driver,
        lambda: plan.wrap_source(make_stream()),
        policy=RestartPolicy(seed=seed),
        metrics_sink=sys.stderr,
    )
    print(f"chaos seed {seed}: plan {plan.faults} (workdir {workdir})")
    res = rec.run(collect_outputs=False)
    print(
        f"chaos run survived: {rec.restarts} restart(s), "
        f"{rec.steps_replayed} WAL step(s) replayed, "
        f"{rec.steps_dropped} step(s) dropped"
    )
    return res


def _run_with_driver(params, stream, *, num_users, num_items, mesh):
    """The --telemetry-port path: same MF job, run under the
    StreamingDriver envelope so the unified plane is live (step/event
    counters, pull→push latency histogram, ingest counters, host-side
    spans — all scrapeable on /metrics while this trains)."""
    from flink_parameter_server_tpu.core.store import ShardedParamStore
    from flink_parameter_server_tpu.models.matrix_factorization import (
        OnlineMatrixFactorization,
        SGDUpdater,
    )
    from flink_parameter_server_tpu.training.driver import (
        DriverConfig,
        StreamingDriver,
    )
    from flink_parameter_server_tpu.utils.initializers import (
        ranged_random_factor,
    )

    dim = params.get_int("dim", 32)
    logic = OnlineMatrixFactorization(
        num_users, dim,
        updater=SGDUpdater(params.get_float("lr", 0.05)),
        mesh=mesh,
    )
    store = ShardedParamStore.create(
        num_items, (dim,),
        init_fn=ranged_random_factor(1, (dim,)),
        mesh=mesh,
        scatter_impl=params.get("scatter", "xla"),
        layout=params.get("layout", "dense"),
    )
    driver = StreamingDriver(
        logic, store,
        config=DriverConfig(
            dump_model=False,
            presort=params.get_bool("presort", False),
            steps_per_call=params.get_int("steps-per-call", 1),
        ),
    )
    return driver.run(stream)


def main():
    params = Parameters.from_env().merged_with(
        Parameters.from_args(sys.argv[1:])
    )
    path = params.get("path")
    sock = params.get("socket")
    data = None
    if sock:
        # the socket branch never reads --path/--epochs; silently
        # ignoring them would train on different data/passes than the
        # user asked for — refuse the contradictory combination
        clash = [
            f"--{key}" for key in ("path", "epochs") if key in params
        ]
        if clash:
            raise SystemExit(
                f"--socket streams unbounded live data and is "
                f"incompatible with {', '.join(clash)} (bounded-file "
                f"options); drop one side"
            )
        num_users = params.get_int("num-users", 2000)
        num_items = params.get_int("num-items", 3000)
    else:
        if path:
            data = load_movielens(path)
        else:
            data = synthetic_ratings(2000, 3000, 200_000, rank=8, seed=0)
        num_users = int(data["user"].max()) + 1
        num_items = int(data["item"].max()) + 1

    import jax

    mesh = None
    if len(jax.devices()) > 1:
        mesh = make_mesh()  # all devices on dp; ps=1

    telemetry_server = None
    if "telemetry-port" in params:
        from flink_parameter_server_tpu.telemetry import TelemetryServer

        telemetry_server = TelemetryServer(
            port=params.get_int("telemetry-port", 0)
        ).start()
        print(
            f"telemetry live: http://{telemetry_server.host}:"
            f"{telemetry_server.port}/metrics (and /healthz)"
        )

    if sock:
        from flink_parameter_server_tpu.data.socket import (
            batches_from_records,
            socket_text_stream,
        )

        host, port = sock.rsplit(":", 1)

        def parse(line):
            u, i, r = line.split(",")
            u, i = int(u), int(i)
            if not (0 <= u < num_users and 0 <= i < num_items):
                # out-of-range ids would clamp (gather) / drop (scatter)
                # SILENTLY inside the jitted step — surface them on the
                # dropped counter like any other malformed record
                return None
            return {
                "user": np.int32(u),
                "item": np.int32(i),
                "rating": np.float32(r),
            }

        def make_stream():
            # a fresh dial per (re)start — socket_text_stream itself
            # reconnects through transient drops (data/socket.py)
            return batches_from_records(
                socket_text_stream(host, int(port)),
                params.get_int("batch", 4096),
                parse,
            )

        stream = make_stream()
    else:
        def make_stream():
            return microbatches(
                data,
                params.get_int("batch", 4096),
                epochs=params.get_int("epochs", 3),
                shuffle_seed=0,
            )

        stream = make_stream()

    if "chaos" in params:
        res = _run_with_chaos(
            params, make_stream, num_users=num_users, num_items=num_items,
            mesh=mesh,
        )
    elif telemetry_server is not None:
        # the telemetry demo runs through the StreamingDriver — the
        # plane's instruments (step counters, pull→push histogram,
        # ingest counters, spans) live on the driver envelope, which
        # the bare ps_online_mf/transform_batched loop bypasses
        res = _run_with_driver(
            params, stream, num_users=num_users, num_items=num_items,
            mesh=mesh,
        )
    else:
        res = ps_online_mf(
            stream,
            num_users=num_users,
            num_items=num_items,
            dim=params.get_int("dim", 32),
            learning_rate=params.get_float("lr", 0.05),
            mesh=mesh,
            collect_outputs=False,
            scatter_impl=params.get("scatter", "xla"),
            layout=params.get("layout", "dense"),
            presort=params.get_bool("presort", False),
            steps_per_call=params.get_int("steps-per-call", 1),
        )
    uf = np.asarray(res.worker_state)
    itf = np.asarray(res.store.values())
    if data is not None:
        pred = np.einsum("ij,ij->i", uf[data["user"]], itf[data["item"]])
        rmse = float(np.sqrt(np.mean((pred - data["rating"]) ** 2)))
        base = float(np.sqrt(np.mean(data["rating"] ** 2)))
        print(f"train RMSE {rmse:.4f} (zero-predictor {base:.4f})")
    else:
        # unbounded socket stream: no held dataset to score against —
        # report the trained shapes + the dropped-record count instead
        print(f"socket stream ended; malformed records dropped: "
              f"{stream.dropped}")
    print(f"user factors {uf.shape}, item factors {itf.shape}")
    if telemetry_server is not None:
        telemetry_server.stop()


if __name__ == "__main__":
    main()
