"""Word2vec skip-gram with negative sampling on the PS.

BASELINE.json config #3: both embedding matrices live on the sharded
store; workers stream pairs and push sparse deltas.  The dedup combiner
keeps high learning rates stable on Zipf-hot vocabularies.
"""
import numpy as np

from flink_parameter_server_tpu.data.text import (
    skipgram_batches,
    synthetic_corpus,
)
from flink_parameter_server_tpu.models.word2vec import IN, train_skipgram


def main():
    vocab = 2000
    tokens = synthetic_corpus(
        vocab, 150_000, num_topics=10, topic_stickiness=0.995, seed=0
    )
    res = train_skipgram(
        skipgram_batches(tokens, vocab, batch_size=1024, window=4,
                         num_negatives=5, epochs=2, seed=0),
        vocab_size=vocab,
        dim=32,
        learning_rate=1.0,
        dedup_scale=True,
        collect_outputs=False,
    )
    emb = np.asarray(res.store.values())[:, IN]
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9)

    # nearest neighbours of a few topic-head words
    for w in [0, 200, 400]:
        sims = emb @ emb[w]
        nn = np.argsort(-sims)[1:6]
        print(f"word {w}: neighbours {nn.tolist()} "
              f"(same topic: {[int(x // 200 == w // 200) for x in nn]})")


if __name__ == "__main__":
    main()
