"""PS-backed streaming sketches over a token stream.

Mirrors the reference's sketch package (SURVEY.md §2 #10): count-min word
counts, bloom co-occurrence similarity, tug-of-war F2, time decay.
"""
import jax.numpy as jnp
import numpy as np

from flink_parameter_server_tpu.core.transform import transform_batched
from flink_parameter_server_tpu.data.text import (
    cooccurrence_pairs,
    synthetic_corpus,
)
from flink_parameter_server_tpu.models.sketches import (
    BloomCooccurrence,
    CountMinConfig,
    CountMinSketch,
    TugOfWarConfig,
    TugOfWarSketch,
    decay,
)


def key_batches(keys, batch=1024):
    for s in range(0, len(keys) - batch + 1, batch):
        yield {"key": keys[s : s + batch], "mask": np.ones(batch, bool)}


def main():
    vocab = 400
    tokens = synthetic_corpus(vocab, 100_000, num_topics=8,
                              topic_stickiness=0.995, seed=3)

    # word counts
    cms = CountMinSketch(CountMinConfig(width=8192, depth=4, seed=0))
    words = transform_batched(key_batches(tokens), cms, cms.make_store(),
                              collect_outputs=False)
    true = np.bincount(tokens, minlength=vocab)
    hot = np.argsort(true)[-3:]
    est = np.asarray(cms.query(words.store, jnp.asarray(hot, jnp.int32)))
    print("count-min hottest words:", dict(zip(hot.tolist(), est.tolist())),
          "true:", true[hot].tolist())

    # co-occurrence similarity
    bloom = BloomCooccurrence(CountMinConfig(width=1 << 15, depth=4, seed=1))
    pairs = transform_batched(cooccurrence_pairs(tokens, window=2), bloom,
                              bloom.make_store(), collect_outputs=False)
    wpt = vocab // 8
    a = jnp.asarray([0, 0])
    b = jnp.asarray([1, wpt])  # same-topic vs cross-topic neighbour
    sims = bloom.similarity(pairs.store, words.store, cms, a, b)
    print(f"similarity(word0, word1 same-topic)={float(sims[0]):.3f}  "
          f"(word0, word{wpt} cross-topic)={float(sims[1]):.3f}")

    # F2 second moment
    tow = TugOfWarSketch(TugOfWarConfig(groups=8, per_group=32, seed=2))
    f2 = transform_batched(key_batches(tokens), tow, tow.make_store(),
                           collect_outputs=False)
    print(f"F2 estimate {float(tow.estimate_f2(f2.store)):.3g} "
          f"true {float((true.astype(np.float64) ** 2).sum()):.3g}")

    # time-aware decay tick
    decayed = decay(words.store, 0.5)
    print("after decay(0.5), hottest estimate:",
          float(cms.query(decayed, jnp.asarray([int(hot[-1])]))[0]))


if __name__ == "__main__":
    main()
