"""PS-backed streaming sketches over a token stream — through the
workload registry.

Mirrors the reference's sketch package (SURVEY.md §2 #10).  The
count-min layer is the registered "sketch" workload
(``workloads/registry.py``), so the same object runs single-process,
on a live multi-shard cluster (``--cluster``, counts checked
INTEGER-EXACT against the pure-numpy ground truth — increments, not
fp32 deltas), and behind the ``query``/``topk`` serving verbs
(``--serve``).  The classic single-process tour (bloom co-occurrence
similarity, tug-of-war F2, time decay) still runs below it.
"""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=400)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--cluster", action="store_true",
                    help="run the count-min layer on a 2-shard PS "
                         "cluster and verify integer-exact counts")
    ap.add_argument("--serve", action="store_true",
                    help="also open the TCP query/topk endpoint "
                         "(implies --cluster)")
    args = ap.parse_args()
    if args.serve:
        args.cluster = True

    from flink_parameter_server_tpu.workloads import (
        WorkloadParams,
        build_cluster_driver,
        create_workload,
    )

    params = WorkloadParams(
        rounds=args.rounds, batch=args.batch, num_items=args.vocab,
        seed=3,
    )
    wl = create_workload("sketch", params)
    tokens = wl._tokens()
    true = np.bincount(tokens, minlength=args.vocab)
    hot = np.argsort(true)[-3:]

    # ground-truth sketch table (pure numpy — integers)
    table = wl.oracle_values()
    est = table.reshape(-1)[wl.cells_np(hot)].min(axis=1)
    print("count-min hottest words:",
          dict(zip(hot.tolist(), est.astype(int).tolist())),
          "true:", true[hot].tolist())

    if args.cluster:
        from flink_parameter_server_tpu.cluster.driver import (
            ClusterConfig,
        )

        driver = build_cluster_driver(
            wl,
            config=ClusterConfig(
                num_shards=2, num_workers=2, staleness_bound=0,
            ),
        )
        with driver:
            result = driver.run(wl.batches())
            exact = bool(np.array_equal(result.values, table))
            print(f"cluster run: {result.events} increments over "
                  f"{result.rounds} rounds on 2 shards; "
                  f"integer-exact vs ground truth: {exact}")
            if not exact:
                raise SystemExit("sketch counts diverged from truth")
            if args.serve:
                from flink_parameter_server_tpu.workloads import (
                    WorkloadServingClient,
                    serve_workload,
                )

                client = driver._make_client(worker="serve")
                server = serve_workload(wl, client)
                try:
                    sc = WorkloadServingClient(
                        server.host, server.port
                    )
                    print("served query:", dict(zip(
                        hot.tolist(), sc.query(hot.tolist())
                    )))
                    print("served top-4:", sc.topk(4))
                finally:
                    server.stop()
                    client.close()

    # -- the classic single-process tour -------------------------------------
    import jax.numpy as jnp

    from flink_parameter_server_tpu.core.transform import (
        transform_batched,
    )
    from flink_parameter_server_tpu.data.text import cooccurrence_pairs
    from flink_parameter_server_tpu.models.sketches import (
        BloomCooccurrence,
        CountMinConfig,
        CountMinSketch,
        TugOfWarConfig,
        TugOfWarSketch,
        decay,
    )

    def key_batches(keys, batch=1024):
        for s in range(0, len(keys) - batch + 1, batch):
            yield {"key": keys[s: s + batch],
                   "mask": np.ones(batch, bool)}

    cms = CountMinSketch(CountMinConfig(width=8192, depth=4, seed=0))
    words = transform_batched(key_batches(tokens), cms,
                              cms.make_store(), collect_outputs=False)

    bloom = BloomCooccurrence(
        CountMinConfig(width=1 << 15, depth=4, seed=1)
    )
    pairs = transform_batched(
        cooccurrence_pairs(tokens, window=2), bloom,
        bloom.make_store(), collect_outputs=False,
    )
    wpt = args.vocab // 4  # words per topic (workload topics = 4)
    a = jnp.asarray([0, 0])
    b = jnp.asarray([1, wpt])  # same-topic vs cross-topic neighbour
    sims = bloom.similarity(pairs.store, words.store, cms, a, b)
    print(f"similarity(word0, word1 same-topic)={float(sims[0]):.3f}  "
          f"(word0, word{wpt} cross-topic)={float(sims[1]):.3f}")

    tow = TugOfWarSketch(TugOfWarConfig(groups=8, per_group=32, seed=2))
    f2 = transform_batched(key_batches(tokens), tow, tow.make_store(),
                           collect_outputs=False)
    print(f"F2 estimate {float(tow.estimate_f2(f2.store)):.3g} "
          f"true {float((true.astype(np.float64) ** 2).sum()):.3g}")

    decayed = decay(words.store, 0.5)
    print("after decay(0.5), hottest estimate:",
          float(cms.query(decayed, jnp.asarray([int(hot[-1])]))[0]))


if __name__ == "__main__":
    main()
