"""Production-envelope job: StreamingDriver around the MF loop.

The reference gets its operational envelope from Flink (web-UI metrics,
checkpointing — which famously does NOT cover iterative streams — and
job lifecycle; SURVEY.md §1 L1, §5).  This example is that envelope
here, PS-aware: periodic orbax checkpoints, step metrics, the NaN guard,
preemption-safe shutdown, and crash→resume — demonstrated by actually
"crashing" the stream mid-run and resuming from the durable checkpoint.

Usage (ParameterTool-style args — utils/config.py):
    python examples/production_driver.py [--dim 16] [--batch 2048]
        [--steps-per-call 8] [--checkpoint-every 16] [--ckpt-dir DIR]

``--steps-per-call K`` runs the envelope at dispatch granularity (one
host round trip per K microbatches — measured 50x at 75 ms host RTT,
results/cpu/steps_per_call_latency.md); checkpoint/metrics/NaN cadences
round up to dispatch boundaries.
"""
import os
import shutil
import sys
import tempfile

import numpy as np

from flink_parameter_server_tpu.core.store import ShardedParamStore
from flink_parameter_server_tpu.data.movielens import synthetic_ratings
from flink_parameter_server_tpu.data.streams import microbatches
from flink_parameter_server_tpu.models.matrix_factorization import (
    OnlineMatrixFactorization,
    SGDUpdater,
)
from flink_parameter_server_tpu.training.driver import (
    DriverConfig,
    StreamingDriver,
)
from flink_parameter_server_tpu.utils.config import Parameters
from flink_parameter_server_tpu.utils.initializers import ranged_random_factor


class SimulatedPreemption(Exception):
    """Dedicated crash sentinel: a plain RuntimeError would be
    indistinguishable from the driver's own TrainingDiverged (a
    RuntimeError subclass), and masking real divergence as the demo
    crash would be exactly the observability bug this example warns
    against."""


def main():
    params = Parameters.from_env().merged_with(
        Parameters.from_args(sys.argv[1:])
    )
    num_users, num_items = 2000, 3000
    dim = params.get_int("dim", 16)
    batch = params.get_int("batch", 2048)
    n_batches = params.get_int("batches", 48)
    ckpt_every = params.get_int("checkpoint-every", 16)
    K = params.get_int("steps-per-call", 8)
    data = synthetic_ratings(
        num_users, num_items, n_batches * batch, rank=8, seed=0
    )

    def fresh_driver(ckpt_dir):
        logic = OnlineMatrixFactorization(
            num_users, dim, updater=SGDUpdater(0.05)
        )
        store = ShardedParamStore.create(
            num_items, (dim,), init_fn=ranged_random_factor(0, (dim,))
        )
        cfg = DriverConfig(
            checkpoint_dir=ckpt_dir,
            checkpoint_every=ckpt_every,
            metrics_every=16,
            nan_check_every=8,
            steps_per_call=K,
        )
        return StreamingDriver(
            logic, store, config=cfg, metrics_sink=sys.stdout
        )

    ckpt_dir = params.get("ckpt-dir")
    own_tmpdir = ckpt_dir is None
    if own_tmpdir:
        ckpt_dir = tempfile.mkdtemp(prefix="fps_ckpt_")
    elif os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir):
        # stale checkpoints would make BOTH runs resume a prior run's
        # final state and the demo would silently train on nothing
        raise SystemExit(
            f"--ckpt-dir {ckpt_dir} is not empty; point at a fresh "
            f"directory (this demo exercises crash->resume from its "
            f"own checkpoints)"
        )
    stream = list(microbatches(data, batch, shuffle_seed=0))

    # --- run 1: "crash" partway through (the stream iterator dies),
    # but only after at least one durable checkpoint exists: cadences
    # round UP to dispatch boundaries, so the first durable save lands
    # at ceil(checkpoint_every / K) * K steps
    first_durable = -(-ckpt_every // K) * K
    crash_at = max((2 * len(stream)) // 3, first_durable + 1)
    if crash_at >= len(stream):
        raise SystemExit(
            f"--batches {n_batches} is too short to crash after the "
            f"first durable checkpoint (step {first_durable}); raise "
            f"--batches or lower --checkpoint-every/--steps-per-call"
        )
    driver = fresh_driver(ckpt_dir)

    def dying():
        for i, b in enumerate(stream):
            if i == crash_at:
                raise SimulatedPreemption()
            yield b

    try:
        driver.run(dying())
    except SimulatedPreemption:
        print(f"crashed at batch {crash_at}; driver rolled back to "
              f"durable step {driver.step_idx}")

    # --- run 2: fresh process/driver resumes from the checkpoint ------
    driver2 = fresh_driver(ckpt_dir)
    assert driver2.resume(), "no durable checkpoint found"
    print(f"resumed at step {driver2.step_idx}; re-feeding the same "
          f"stream (cursor fast-forwards)")
    res = driver2.run(iter(stream))
    assert driver2.step_idx == len(stream), driver2.step_idx

    uf = np.asarray(res.worker_state)
    itf = np.asarray(res.store.values())
    pred = np.einsum("ij,ij->i", uf[data["user"]], itf[data["item"]])
    rmse = float(np.sqrt(np.mean((pred - data["rating"]) ** 2)))
    base = float(np.sqrt(np.mean(data["rating"] ** 2)))
    print(f"resumed-run RMSE {rmse:.4f} (zero-predictor {base:.4f})")
    from flink_parameter_server_tpu.training.checkpoint import (
        JobCheckpointManager,
    )

    print(f"durable checkpoints: {JobCheckpointManager(ckpt_dir).all_steps()}")
    if own_tmpdir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
