"""Train-while-serve: online MF with a live top-K recommendation server.

The serving subsystem's canonical demo (docs/serving.md): a
StreamingDriver trains online matrix factorization while the attached
serving service answers top-K queries from versioned table snapshots —
in-process through a :class:`ServingClient`, and over TCP through the
line-protocol :class:`ServingServer` (the serve-side mirror of the
ingest socket).

Usage (ParameterTool-style args — utils/config.py)::

    python examples/serve_recommendations.py
        [--num-users 2000] [--num-items 5000] [--dim 32]
        [--ratings 300000] [--batch 4096] [--epochs 3] [--k 10]
        [--publish-every 4] [--port 0]      # 0 = ephemeral
        [--queries 32]                      # in-process demo queries

Runs on any backend (CPU works: ``JAX_PLATFORMS=cpu``).
"""
import sys
import threading

import numpy as np

from flink_parameter_server_tpu import (
    DriverConfig,
    ShardedParamStore,
    StreamingDriver,
)
from flink_parameter_server_tpu.data.movielens import synthetic_ratings
from flink_parameter_server_tpu.data.streams import microbatches
from flink_parameter_server_tpu.models.matrix_factorization import (
    OnlineMatrixFactorization,
    SGDUpdater,
)
from flink_parameter_server_tpu.serving import ServingServer
from flink_parameter_server_tpu.serving.server import tcp_request
from flink_parameter_server_tpu.utils.config import Parameters
from flink_parameter_server_tpu.utils.initializers import (
    ranged_random_factor,
)


def main():
    params = Parameters.from_env().merged_with(
        Parameters.from_args(sys.argv[1:])
    )
    num_users = params.get_int("num-users", 2000)
    num_items = params.get_int("num-items", 5000)
    dim = params.get_int("dim", 32)
    k = params.get_int("k", 10)

    logic = OnlineMatrixFactorization(
        num_users, dim, updater=SGDUpdater(0.05)
    )
    store = ShardedParamStore.create(
        num_items, (dim,), init_fn=ranged_random_factor(1, (dim,))
    )
    driver = StreamingDriver(
        logic, store, config=DriverConfig(dump_model=False)
    )
    service = driver.serve_with(
        publish_every=params.get_int("publish-every", 4)
    )
    client = service.client()

    data = synthetic_ratings(
        num_users, num_items, params.get_int("ratings", 300_000),
        rank=8, seed=0,
    )
    batches = microbatches(
        data,
        params.get_int("batch", 4096),
        epochs=params.get_int("epochs", 3),
        shuffle_seed=0,
    )
    trainer = threading.Thread(
        target=lambda: driver.run(batches, collect_outputs=False),
        daemon=True,
    )
    trainer.start()

    # -- queries WHILE training ------------------------------------------
    service.wait_for_snapshot(120, min_version=2)
    rng = np.random.default_rng(0)
    for _ in range(params.get_int("queries", 32)):
        user = int(rng.integers(0, num_users))
        # exclude the user's already-rated items (first 16 shown here)
        seen = data["item"][data["user"] == user][:16].tolist()
        res = client.top_k(user, k=k, exclude=seen)
        print(
            f"user {user:5d}  top-{k} {res.item_ids.tolist()}  "
            f"(snapshot v{res.version}, {res.staleness} steps stale)"
        )
    trainer.join()

    # -- and over TCP, from the FINAL model -------------------------------
    server = ServingServer(
        service, port=params.get_int("port", 0)
    ).start()
    print(f"serving on {server.host}:{server.port}")
    resp = tcp_request(server.host, server.port, f"topk 0 {k}")
    print(f"tcp answer: {resp}")
    print(service.metrics.emit())
    server.stop()
    service.stop()


if __name__ == "__main__":
    main()
