"""Online passive-aggressive binary classification.

Mirrors the reference's ``PassiveAggressiveParameterServer.transformBinary``
(SURVEY.md §2 #9): sparse examples, pull only the present feature ids,
PA-I updates, prediction stream out.
"""
import numpy as np

from flink_parameter_server_tpu.data.streams import sparse_feature_batches
from flink_parameter_server_tpu.models.passive_aggressive import (
    PARule,
    transform_binary,
)


def main():
    rng = np.random.default_rng(0)
    F = 100
    w_true = rng.normal(0, 1, F)
    X = rng.normal(0, 1, (4000, F)).astype(np.float32)
    X[rng.random(X.shape) < 0.7] = 0.0  # sparse
    y = np.sign(X @ w_true + 1e-9)

    losses = []
    res = transform_binary(
        sparse_feature_batches(X, y, 128, epochs=3),
        num_features=F,
        rule=PARule("PA-I", C=1.0),
        on_step=lambda i, o: losses.append(float(np.mean(np.asarray(o["loss"])))),
        collect_outputs=False,
    )
    w = np.asarray(res.store.values())
    acc = float(np.mean(np.sign(X @ w) == y))
    print(f"hinge loss {losses[0]:.3f} -> {np.mean(losses[-10:]):.3f}; "
          f"train accuracy {acc:.3%}")


if __name__ == "__main__":
    main()
