"""Online passive-aggressive binary classification — through the
workload registry.

Mirrors the reference's ``PassiveAggressiveParameterServer
.transformBinary`` (SURVEY.md §2 #9): sparse examples, pull only the
present feature ids, PA-I updates, prediction stream out.  The
workload is resolved from ``workloads/registry.py`` ("pa"), so the
exact same object can run three ways:

  * default — the single-process StreamingDriver path;
  * ``--cluster`` — a 2-shard BSP parameter-server cluster (real TCP),
    whose final weight vector is checked BITWISE against the
    single-process run (the workload's parity contract);
  * ``--serve`` (implies ``--cluster``) — a live ``predict`` serving
    endpoint (workloads/serving.py) answering sparse-margin queries
    over TCP while the table sits on the shards.
"""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--features", type=int, default=100)
    ap.add_argument("--rounds", type=int, default=24)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--cluster", action="store_true",
                    help="run on a 2-shard PS cluster and verify "
                         "bitwise parity vs the streaming run")
    ap.add_argument("--serve", action="store_true",
                    help="also open the TCP predict endpoint "
                         "(implies --cluster)")
    args = ap.parse_args()
    if args.serve:
        args.cluster = True

    from flink_parameter_server_tpu.workloads import (
        WorkloadParams,
        build_cluster_driver,
        create_workload,
    )
    from flink_parameter_server_tpu.workloads.pa import _pa_stream

    params = WorkloadParams(
        rounds=args.rounds, batch=args.batch,
        num_items=args.features, seed=0,
    )
    wl = create_workload("pa", params)
    X, y = _pa_stream(params)

    # the single-process run (the StreamingDriver oracle)
    w = np.asarray(wl.oracle_values())
    margins = X @ w
    acc = float(np.mean(np.sign(margins) == y))
    loss = float(np.mean(np.maximum(0.0, 1.0 - y * margins)))
    print(f"final hinge loss {loss:.3f}; train accuracy {acc:.3%}")

    if not args.cluster:
        return

    from flink_parameter_server_tpu.cluster.driver import ClusterConfig

    driver = build_cluster_driver(
        wl,
        config=ClusterConfig(
            num_shards=2, num_workers=1, staleness_bound=0,
        ),
    )
    with driver:
        result = driver.run(wl.batches())
        bitwise = bool(np.array_equal(result.values, w))
        print(f"cluster run: {result.events} events over "
              f"{result.rounds} rounds on 2 shards; "
              f"bitwise parity vs streaming: {bitwise}")
        if not bitwise:
            raise SystemExit("cluster/streaming parity violated")
        if args.serve:
            from flink_parameter_server_tpu.workloads import (
                WorkloadServingClient,
                serve_workload,
            )

            client = driver._make_client(worker="serve")
            server = serve_workload(wl, client)
            try:
                sc = WorkloadServingClient(server.host, server.port)
                # serve two live examples from the training stream
                ex = []
                for i in range(2):
                    nz = np.nonzero(X[i])[0][:6]
                    ex.append([(int(f), float(X[i, f])) for f in nz])
                served = sc.predict(ex)
                print("served margins:",
                      [f"{m:.4f}" for m in served],
                      f"(labels {y[:2].astype(int).tolist()})")
            finally:
                server.stop()
                client.close()


if __name__ == "__main__":
    main()
